"""SolverService: bounded admission, a mesh-aware placement tier
(replica worker pool + spmd routing), same-bucket batch coalescing,
deadlines, retries with backoff, and circuit-breaker recovery.

Execution model (a pool of supervised replica workers plus an optional
sharded lane — the multi-device serving tier ROADMAP item 1 asks for):

* ``submit()`` validates (non-finite A/B -> immediate
  :class:`~slate_tpu.exceptions.InvalidInput`, before any queue or
  compile cost is paid; ``validate=False`` opts out), buckets the
  request (`buckets.bucket_for`), and enqueues.  A full service (total
  queued across every replica at ``max_queue``) rejects IMMEDIATELY
  with :class:`Rejected` — backpressure belongs at admission, not at a
  timeout deep in the pipeline.
* **Placement** (`serve/placement.PlacementPolicy`): small buckets are
  data-parallel-replicated — each of ``replicas`` workers owns a queue
  and pins its dispatches to one device, and admission routes to the
  least-loaded (or round-robin) replica, excluding replicas whose
  breaker for that bucket is open (``serve.replicated_dispatch``).
  Large-n requests (``n >= shard_threshold``) or ``sharded=True``
  submits route to the *sharded lane*: a dedicated worker whose bucket
  executables trace the ``parallel/`` spmd drivers under shard_map on
  the configured ``"PxQ"`` submesh (``serve.routed_sharded``; the
  BucketKey carries ``mesh`` so executables, manifests and artifacts
  key per mesh shape).  The default policy (1 replica, no mesh) is the
  single-worker service, behavior-identical to the pre-placement tier.
* Each replica worker pops the oldest *eligible* request from ITS
  queue (one whose retry backoff has elapsed), waits up to
  ``batch_window_s`` for company, then coalesces every queued request
  with the same BucketKey (up to ``batch_max``) into one batch padded
  to the fixed batch point (`buckets.batch_bucket`), so only two
  executables exist per bucket per device and warmed steady state
  never compiles.  Sharded buckets never coalesce: their batch point
  is 1 — shape parallelism comes from the mesh, throughput from the
  replicas.
* **Supervision**: every worker runs under a guard that catches ANY
  death (including the ``worker_death`` fault site), re-enqueues that
  replica's in-flight requests that still have retry budget, fails the
  rest fast with a typed error, respawns the worker, and counts
  ``serve.worker_restarts`` — no future ever hangs.
* Deadlines: a request whose deadline passes while still QUEUED is
  cancelled with :class:`DeadlineExceeded`
  (``serve.deadline_miss_queued``) — it never starts.  A request that
  finishes past its deadline still delivers its result (XLA dispatches
  cannot be cancelled mid-flight) but counts
  ``serve.deadline_miss_late``.  ``serve.deadline_miss`` stays the
  total of both.
* Failures: an executable exception re-enqueues the batch's requests
  on their own replica while they have ``retries`` left, each delayed
  by exponential backoff with decorrelated jitter
  (:func:`decorrelated_backoff`, seeded); after the budget each
  request falls back to the direct driver (``serve.fallbacks``).
* **Circuit breaker** (`buckets.Breaker`, keyed by BucketKey *per
  replica*): a bucket whose batched path fails ``degrade_after``
  consecutive times on one replica opens that replica's breaker —
  its requests route direct, and admission steers NEW requests for
  the bucket to healthy replicas — but after ``breaker_cooldown_s``
  the breaker half-opens and the next batch probes the batched path;
  one healthy probe closes it again.  Degradation is recoverable and
  local: one sick replica never degrades the whole bucket fleet.
* A nonzero per-item ``info`` raises
  :class:`~slate_tpu.exceptions.NumericalError` on that item's future
  only (no retry: the failure is deterministic); a non-finite solution
  for finite inputs (the ``result_corrupt`` fault site) re-solves that
  item on the direct driver instead of delivering garbage.
* **Admission plane** (`serve/admission.py`, optional): with a tenant
  spec (``SLATE_TPU_TENANTS`` / ``Option.ServeTenantQuota``) each
  request carries ``tenant``/``priority``; lane FIFOs become
  per-tenant weighted-fair queues, token-bucket quotas and queue-share
  caps make :class:`Rejected` per-tenant (a hot tenant sheds its own
  load first), and under sustained deadline-budget burn the overload
  controller refuses lowest-priority-first with a typed :class:`Shed`
  (breaker-style hysteresis — never flaps).  With adaptation on
  (``SLATE_TPU_ADAPTIVE`` / ``Option.ServeAdaptiveWindow``), each
  bucket's coalesce window is AIMD-tuned against the p99 budget with
  ``batch_window_s`` as the ceiling (Clipper's shape), every decision
  recorded.  Unconfigured, the plane is None and every path is
  byte-identical to the pre-admission tier.
* **Integrity plane** (``slate_tpu/integrity``, optional): with an
  ``Option.ServeIntegrity`` / ``SLATE_TPU_INTEGRITY`` policy
  (``off | sample=<p> | full``, optional ``,abft``), delivered
  gesv/posv solves are *certified* — the residual fence, or the cheap
  checksum relations when the bucket was built with ABFT cores — and
  a failed certificate NEVER reaches the client: the request
  re-executes, hedged to a different replica when one exists.  Each
  lane's certificate-failure EWMA (:class:`IntegrityScore`, distinct
  from the breaker: the breaker sees exceptions and NaNs, the score
  sees certified-wrong answers) quarantines the lane at admission and
  probes it back like a half-open breaker.  Queued requests at
  deadline risk (age past the bucket's p99) are duplicated onto a
  second lane, first correct result wins.  Unconfigured, the plane is
  None and every delivery pays one branch.
* :meth:`SolverService.health` returns a liveness/readiness snapshot
  (total + per-replica queue depth, per-replica worker liveness /
  restarts / dispatch counts / breaker states, recent failure rate)
  for external probes — including the cold-start **readiness phase**
  ``cold`` -> ``restoring`` -> ``ready``: a service whose cache has an
  artifact store (``SLATE_TPU_ARTIFACTS``) restores every manifest
  entry on :meth:`start` in a background thread — priming every
  replica's device, and skipping manifest entries whose mesh shape
  this process cannot realize — before reporting ``ready``, so an
  orchestrator can gate traffic until the warmed executable set is
  live.  Requests submitted while ``restoring`` are still served
  (possibly paying a compile); the phase is a gate for callers, not an
  admission check.

Every exception set on a future carries structured context
(``routine``/``bucket``/``attempt``, :meth:`SlateError.with_context`).

Metrics: ``serve.queue_depth`` gauge (total) +
``serve.replica.<i>.queue_depth`` per replica (the sharded lane is
``serve.replica.sharded.*``), ``serve.requests``,
``serve.replicated_dispatch`` / ``serve.routed_sharded`` placement
counters, ``serve.replica.<i>.dispatched``, ``serve.batched``,
``serve.batched_requests``, ``serve.batch_pad``,
``serve.bucket_pad_waste``, ``serve.deadline_miss`` (+ ``_queued`` /
``_late`` split), ``serve.rejected``, ``serve.invalid_input``,
``serve.retries`` + ``serve.retry_backoff_s`` timer,
``serve.fallbacks``, ``serve.worker_restarts``,
``serve.breaker_open`` / ``half_open`` / ``closed`` (and the legacy
``serve.degraded`` alias for open transitions),
``serve.numerical_errors``, ``serve.corrupt_result``; per-bucket
compile/run split via the cache's instrumented executables;
``faults.injected.<site>`` from aux/faults when chaos is on.  The
admission plane adds ``serve.shed``, ``serve.rejected_quota`` /
``serve.rejected_share``, capped per-tenant families
``serve.tenant.<id>.{admitted,shed,rejected,slo_burn.*}`` +
``serve.latency.tenant.<id>.total`` histograms
(``serve.tenant_overflow`` past the cap), ``serve.overload.level``
gauge + ``.enter``/``.exit`` counters, and per-bucket
``serve.adaptive.<label>.window_s`` gauges with ``.widen``/``.shrink``
change counters (``serve.adaptive.changes`` total).  The integrity
plane adds ``serve.integrity.checked`` / ``serve.integrity.fail`` /
``serve.integrity.recovered`` / ``serve.integrity.abandoned``,
quarantine transitions ``serve.integrity.quarantined`` /
``serve.integrity.unquarantined`` (+ per-replica
``serve.replica.<i>.quarantined`` / ``.unquarantined``), and the
hedging triple ``serve.hedge.sent`` / ``serve.hedge.won`` /
``serve.hedge.wasted``; ``serve.drained`` / ``serve.drain_abandoned``
count graceful-drain outcomes at :meth:`stop`.

Latency observability (this file is where the split is measured):
``serve.latency.<bucket>.queued`` / ``.execute`` / ``.total``
histograms per bucket label plus ``serve.latency.replica.<i>.total``
per lane (``metrics.observe_hist``, log-spaced fixed buckets —
``tools/latency_report.py`` renders the percentile table), the
``serve.replica.<i>.oldest_queued_s`` head-of-line age gauge, and the
``serve.slo_burn.{requests,over_50,over_80,exhausted}`` deadline-budget
burn tiers.  With ``aux/spans`` on (``SLATE_TPU_TRACE_RING=N``) every
request carries a trace id and records the full lifecycle span chain
(``request`` -> ``admit``/``queued``/``coalesce``/``execute`` |
``direct``/``backoff`` + breaker instants) into the bounded ring;
``spans.export_chrome(path)`` renders one Perfetto lane per
replica/worker.  All of it is one branch per call site when off.
"""

from __future__ import annotations

import contextlib
import functools
import os
import random
import threading
import time
from collections import OrderedDict, deque
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Callable, Deque, Dict, List, Optional, Union

import numpy as np

from ..aux import devmon, faults, metrics, spans, sync
from ..exceptions import InvalidInput, NumericalError, SlateError
from ..integrity import abft as _abft
from ..integrity import policy as _integ
from . import admission as _adm
from . import buckets as _bk
from .cache import ExecutableCache, direct_call
from .factor_cache import (
    FactorCache,
    FactorEntry,
    cache_from_options,
    factor_only,
    gels_factor_pack,
    matrix_fingerprint,
    residual_ok,
    solve_from_factor,
)
from .factor_cache import record as _fc_record
from .placement import PlacementPolicy


class Rejected(SlateError):
    """Queue-full backpressure: the request was never admitted.  On a
    tenancy-enabled service this is PER-TENANT — a token-bucket quota
    or queue-share violation rejects the hot tenant's request while
    its neighbors keep being admitted."""


class DeadlineExceeded(SlateError):
    """The request's deadline passed before execution started."""


class Shed(SlateError):
    """Load shed under sustained overload: the service's burn EWMA
    crossed a shed tier and this request's priority class is being
    refused at admission (lowest-priority-first;
    ``serve/admission.OverloadController``).  Distinct from
    :class:`Rejected` — the queue may have room, but accepting more
    work at this priority would melt the SLO of what is already
    queued.  Back off and retry, or resubmit at a higher priority."""


#: ceiling for one decorrelated-jitter backoff step, seconds
BACKOFF_CAP_S = 2.0

#: readiness phases (health()["phase"]): cold = constructed, warmed
#: set not live; restoring = the start-time artifact/manifest restore
#: pass is running; ready = the restore pass finished (or there was
#: nothing to restore) — orchestrators gate traffic on "ready"
PHASE_COLD = "cold"
PHASE_RESTORING = "restoring"
PHASE_READY = "ready"

#: terminal lane states (health()["lanes"] / ["terminal_lanes"]):
#: draining = remove_replica() is quiescing the lane; removed = gone.
#: Live lanes report state "live" — a vanished row would make
#: scale-down indistinguishable from a crash
LANE_LIVE = "live"
LANE_DRAINING = "draining"
LANE_REMOVED = "removed"


def _scale_policy_armed() -> bool:
    """Cheap pre-check for the elastic capacity plane: is a non-off
    ``SLATE_TPU_SCALE`` / ``Option.ServeScale`` spec present?  Kept
    separate from the real parser so the off path never imports the
    scale/ package at all (zero-overhead-off contract)."""
    from ..enums import Option
    from ..options import get_option

    spec = os.environ.get("SLATE_TPU_SCALE")
    if spec is None:
        spec = str(get_option(None, Option.ServeScale) or "")
    spec = spec.strip().lower()
    return bool(spec) and spec not in ("0", "off", "false", "no")


def decorrelated_backoff(
    rng: random.Random, prev_s: float, base_s: float,
    cap_s: float = BACKOFF_CAP_S,
) -> float:
    """One step of exponential backoff with decorrelated jitter
    (Brooker, AWS Architecture Blog 2015): ``sleep_{k+1} = min(cap,
    U(base, 3 * sleep_k))`` with ``sleep_0 = base``.  Pure in ``rng``,
    so a seeded RNG replays the exact delay sequence — the chaos tests
    assert determinism through this function."""
    hi = max(base_s, 3.0 * prev_s)
    return min(cap_s, rng.uniform(base_s, hi))


@dataclass(eq=False)
class _Request:
    # eq=False: requests are identities, not values — the queues'
    # remove()-based sweep/coalesce must match THIS request (the
    # dataclass-generated __eq__ would compare the ndarray operands,
    # which raises on truthiness and could alias equal requests)
    routine: str
    key: Optional[_bk.BucketKey]  # None => direct-only (e.g. gels m < n)
    A: np.ndarray
    B: np.ndarray
    m: int
    n: int
    nrhs: int
    future: Future = field(default_factory=Future)
    deadline: Optional[float] = None  # absolute time.monotonic()
    retries: int = 0
    attempt: int = 0  # batched attempts so far (error context)
    backoff_s: float = 0.0  # last backoff delay (decorrelated jitter state)
    not_before: float = 0.0  # monotonic eligibility time after a retry
    t_submit: float = field(default_factory=time.monotonic)
    # admission-plane identity (defaults when the plane is off; tenanted
    # marks a request admitted THROUGH the plane, so error context and
    # control-loop accounting only engage where tenancy is real)
    tenant: str = _bk.DEFAULT_TENANT
    priority: int = _bk.PRIO_NORMAL
    tenanted: bool = False
    # factor-cache state (both None/False when the cache is off):
    # the matrix fingerprint of A, and whether admission missed (the
    # request factors via _factor_direct instead of the batched path)
    factor_fp: Optional[str] = None
    factor_miss: bool = False
    # integrity plane (all defaults when the plane is off): certificate
    # failures so far, whether the current re-execution was hedged to a
    # different replica, and — straggler hedging — whether this request
    # IS the duplicate (is_hedge) and the first-result-wins pairing it
    # shares with its twin (hedge_group)
    cert_fails: int = 0
    reexec_hedged: bool = False
    is_hedge: bool = False
    hedge_group: Optional["_HedgeGroup"] = None
    # request-scoped tracing (aux/spans; all None when tracing is off):
    # trace id, root "request" span (admit -> deliver), live "queued" span
    trace: Optional[str] = None
    span: Optional[spans.Span] = None
    qspan: Optional[spans.Span] = None

    def expired(self, now: Optional[float] = None) -> bool:
        return (
            self.deadline is not None
            and (now if now is not None else time.monotonic()) > self.deadline
        )


class _HedgeGroup:
    """First-correct-result-wins pairing of a straggler and its hedge
    (Dean & Barroso, "The Tail at Scale"): the twins share one Future;
    whichever lane delivers first resolves it, the loser's completed
    work counts ``serve.hedge.wasted``, and an exception resolves the
    future only once EVERY member has failed (one slow-or-broken lane
    must never fail a request its twin can still answer)."""

    def __init__(self, members: int = 2):
        # sync.Lock: a plain threading.Lock unless SLATE_TPU_SYNC_CHECK
        # armed the race plane (construction-time decision, zero
        # overhead off)
        self.lock = sync.Lock(name="service._HedgeGroup.lock")
        self.members = members
        self.delivered = False  # guarded by: lock
        self.failed = 0  # guarded by: lock

    def first_result(self) -> bool:
        """Claim the win; False when a twin already delivered."""
        with self.lock:
            sync.guarded(self, "delivered")
            if self.delivered:
                return False
            self.delivered = True
            return True

    def member_failed(self) -> bool:
        """Record one member's failure; True when this was the LAST
        live member and nothing delivered — only then may the caller
        set the exception."""
        with self.lock:
            sync.guarded(self, "failed")
            self.failed += 1
            return not self.delivered and self.failed >= self.members


class _Replica:
    """One serving lane: a queue, a supervised worker, per-bucket
    breakers, and (replicated tier) the device its dispatches pin to.
    The sharded lane is a _Replica named "sharded" with no device pin
    (its executables carry their own mesh placement)."""

    def __init__(self, name: str, device=None):
        self.name = name
        self.device = device
        # integrity plane (None when the plane is off): this lane's
        # certificate-failure EWMA + quarantine state (self-locked)
        self.score: Optional[_integ.IntegrityScore] = None
        # the shared mutable lane state below is owned by the SERVICE's
        # condition lock (SolverService._cond): workers, admission, and
        # health probes all touch it — the annotations are ground truth
        # for the lock-discipline lint rule
        self.q: Deque[_Request] = deque()  # guarded by: _cond
        self.inflight: List[_Request] = []  # guarded by: _cond
        self.breakers: Dict[_bk.BucketKey, _bk.Breaker] = {}  # guarded by: _cond
        # scale-down drain flag: remove_replica() sets it, the worker
        # loop exits on it (re-homing any stragglers first)
        self.stopping = False  # guarded by: _cond
        self.thread: Optional[threading.Thread] = None
        self.restarts = 0
        self.dispatched = 0  # requests this lane executed (incl. direct)
        # metric names precomputed once: the queue gauge is emitted
        # under the service condition lock on every admission/pop
        self.q_gauge = f"serve.replica.{name}.queue_depth"
        self.dispatched_counter = f"serve.replica.{name}.dispatched"
        self.oldest_gauge = f"serve.replica.{name}.oldest_queued_s"
        self.quar_counter = f"serve.replica.{name}.quarantined"
        self.unquar_counter = f"serve.replica.{name}.unquarantined"
        self.removed_counter = f"serve.replica.{name}.removed"
        self.lat_hist = f"serve.latency.replica.{name}.total"
        self.lane = f"replica-{name}"  # span lane label (one Perfetto row)

    def alive(self) -> bool:
        return bool(self.thread is not None and self.thread.is_alive())


class SolverService:
    """Batching solver service over the driver stack.

    Parameters
    ----------
    cache: shared :class:`ExecutableCache` (one per process is the
        point — executables amortize across services); built from
        ``SLATE_TPU_WARMUP`` when omitted.
    max_queue: admission limit over the TOTAL queued across replicas;
        ``submit`` past it raises Rejected.
    batch_max: coalesced batch point (and per-key executable batch).
    batch_window_s: how long a worker lingers for coalescable
        arrivals after popping a lone request.
    dim_floor / nrhs_floor: bucket lattice floors (buckets.py).
    degrade_after: consecutive batched-path failures of one bucket
        on one replica before that replica's breaker opens (its
        requests route direct and admission steers new traffic to
        healthy replicas until the cooldown elapses and a half-open
        probe succeeds).
    breaker_cooldown_s: open -> half-open delay
        (Option.ServeBreakerCooldown when None).
    retry_backoff_s: decorrelated-jitter base delay for batch retries
        (Option.ServeRetryBackoff when None).
    retry_backoff_cap_s: ceiling for one backoff step.
    retry_seed: seeds the backoff jitter RNG (deterministic replay).
    validate: admission-time finiteness checks on A/B
        (Option.ServeValidate when None).
    schedule: factorization schedule the bucket executables trace their
        drivers with (Option.Schedule: "auto"|"flat"|"recursive") —
        part of the BucketKey, so manifests and warmup precompile the
        matching shapes; None reads the Option default.
    precision: solve path for bucket executables ("full"|"mixed";
        Option.ServePrecision when None) — part of the BucketKey, so
        manifests warm the mixed executables too.  A mixed bucket
        factors in low precision and refines on device
        (drivers/mixed.serve_mixed_core); a request whose system
        defeats the refinement comes back non-finite and is re-solved
        on the full-precision direct path (``serve.corrupt_result`` +
        a breaker failure — persistent offenders demote the bucket to
        direct until the breaker heals).  ``submit(precision=...)``
        overrides per request.
    placement: :class:`~slate_tpu.serve.placement.PlacementPolicy`
        (replica count, spmd submesh, shard threshold, selection
        strategy).  None builds one from the Serve* options
        (``ServeReplicas`` / ``ServeMesh`` / ``ServeShardThreshold``),
        with ``replicas=`` below overriding the count.  The default
        (1 replica, no mesh) reproduces the single-worker service.
    replicas: shorthand override for ``placement.replicas`` when no
        explicit policy is passed.
    factor_cache: :class:`~slate_tpu.serve.factor_cache.FactorCache`
        for factor-once/solve-many traffic.  None (default) resolves
        from ``SLATE_TPU_FACTOR_CACHE`` / ``Option.ServeFactorCache*``
        — disabled by default, leaving every path byte-identical to
        the cache-less service (one ``is None`` branch at admission);
        ``False`` disables it explicitly, overriding the env (for
        baseline / A-B services).
        When enabled: gesv/posv full-precision single-device requests
        are fingerprinted at admission; a hit dispatches the trsm-only
        ``phase="solve"`` bucket executable against the cached factor
        on the replica that owns it (when that lane's breaker is open
        the request SPILLS off the batched solve executable — counted
        ``serve.factor_cache.spill`` — onto the direct factor path,
        which reuses the still-healthy factor or refactors if it is
        gone), a miss factors ONCE through
        the direct drivers, caches the factor, and registers the solve
        bucket in the warmup manifest so the steady state is warmed,
        batched, and compile-free.  Every hit is residual-validated —
        a factor that no longer matches A (the ``factor_stale`` chaos
        site) is dropped and the request re-solved direct, never a
        wrong X.
    tenants: admission-plane tenant spec — a grammar string
        (``serve/admission.py``: ``"gold:weight=4;free:rate=20,
        share=0.25"``) or a parsed ``{name: TenantConfig}`` dict.
        None resolves ``Option.ServeTenantQuota`` then the
        ``SLATE_TPU_TENANTS`` env.  Configuring ANY tenant turns the
        plane on: per-lane queues become weighted-fair across tenants,
        token-bucket quotas and queue-share caps reject a hot
        tenant's overflow (per-tenant :class:`Rejected`), and the
        overload controller sheds lowest-priority-first with a typed
        :class:`Shed` under sustained burn.  Unconfigured (the
        default) the plane is OFF — one ``is None`` branch per
        submit, byte-identical behavior.
    adaptive: AIMD batch-window controller
        (``Option.ServeAdaptiveWindow`` / ``SLATE_TPU_ADAPTIVE`` when
        None): per bucket, the coalesce window is tuned from observed
        delivered latency vs. the p99 budget — additive increase
        toward ``batch_window_s`` (the ceiling) while under budget,
        multiplicative decrease when over (Clipper's shape) — with
        every decision recorded (``serve.adaptive.*``).
    latency_budget_s: the service-wide p99 budget the controllers
        compare against (``Option.ServeLatencyBudget`` when None);
        per-request deadlines override it per request.  0 disables
        burn-driven control (the plane still does tenancy).
    integrity: silent-data-corruption defense policy
        (:class:`~slate_tpu.integrity.policy.IntegrityPolicy`, a spec
        string — grammar ``off | sample=<p> | full`` with optional
        ``,abft`` and tuning keys — or ``False`` to disable
        explicitly, overriding the env).  None (default) resolves
        ``SLATE_TPU_INTEGRITY`` then ``Option.ServeIntegrity`` —
        disabled by default: one ``is None`` branch per delivery,
        byte-identical behavior.  When enabled: delivered gesv/posv
        solves are certified (the residual fence, or the cheap ABFT
        checksum relations when the bucket was built with ``abft``),
        a failed certificate NEVER reaches the client (the request
        re-executes, hedged to a different replica when one exists),
        each replica lane carries an :class:`IntegrityScore` whose
        certificate-failure EWMA quarantines the lane at admission
        (probed like a half-open breaker — distinct from the breaker,
        which only sees exceptions and NaNs), and queued requests at
        deadline risk (age past the bucket's p99) are hedged to a
        second replica, first correct result wins
        (``serve.hedge.{sent,won,wasted}``).
    faults_spec: aux/faults grammar string; arms + enables injection
        (Option.Faults when None; empty = no injection).  Injection is
        process-global — the arming service owns it and disarms on
        :meth:`stop`.
    restore_on_start: run the cache's artifact/manifest restore pass
        in a background thread on :meth:`start`, holding
        ``health()["phase"]`` at ``"restoring"`` until it completes.
        None (default) = auto: restore exactly when the cache has an
        artifact store configured (``SLATE_TPU_ARTIFACTS``).  The
        pass never raises — a damaged store degrades to
        recompile-on-traffic and the service still reaches ``ready``.
    start: set False to build paused (tests; call :meth:`start`).
    """

    def __init__(
        self,
        cache: Optional[ExecutableCache] = None,
        max_queue: Optional[int] = None,
        batch_max: Optional[int] = None,
        batch_window_s: Optional[float] = None,
        dim_floor: int = _bk.DIM_FLOOR,
        nrhs_floor: int = _bk.NRHS_FLOOR,
        degrade_after: int = 2,
        breaker_cooldown_s: Optional[float] = None,
        retry_backoff_s: Optional[float] = None,
        retry_backoff_cap_s: float = BACKOFF_CAP_S,
        retry_seed: int = 0,
        validate: Optional[bool] = None,
        schedule: Optional[str] = None,
        precision: Optional[str] = None,
        placement: Optional[PlacementPolicy] = None,
        replicas: Optional[int] = None,
        factor_cache: Union[FactorCache, bool, None] = None,
        factor_arena=None,
        tenants=None,
        adaptive: Optional[bool] = None,
        latency_budget_s: Optional[float] = None,
        integrity=None,
        faults_spec: Optional[str] = None,
        restore_on_start: Optional[bool] = None,
        restore_stuck_after_s: float = 60.0,
        start: bool = True,
    ):
        # None -> the Serve* Option defaults (one source of truth with
        # options.py; api._make_service resolves per-call opts the same way)
        from ..enums import Option, Schedule
        from ..options import get_option

        if cache is None:
            # default cache: Option.ServeArtifacts names the artifact
            # dir (SLATE_TPU_ARTIFACTS env inside the cache otherwise)
            cache = ExecutableCache(
                artifact_dir=get_option(None, Option.ServeArtifacts) or None
            )
        self.cache = cache
        self.max_queue = int(
            max_queue if max_queue is not None
            else get_option(None, Option.ServeQueueLimit)
        )
        self.batch_max = int(
            batch_max if batch_max is not None
            else get_option(None, Option.ServeBatchMax)
        )
        self.batch_window_s = float(
            batch_window_s if batch_window_s is not None
            else get_option(None, Option.ServeBatchWindow)
        )
        self.dim_floor = int(dim_floor)
        self.nrhs_floor = int(nrhs_floor)
        self.degrade_after = int(degrade_after)
        self.breaker_cooldown_s = float(
            breaker_cooldown_s if breaker_cooldown_s is not None
            else get_option(None, Option.ServeBreakerCooldown)
        )
        self.retry_backoff_s = float(
            retry_backoff_s if retry_backoff_s is not None
            else get_option(None, Option.ServeRetryBackoff)
        )
        self.retry_backoff_cap_s = float(retry_backoff_cap_s)
        self.validate = bool(
            validate if validate is not None
            else get_option(None, Option.ServeValidate)
        )
        if schedule is None:
            schedule = get_option(None, Option.Schedule, Schedule.Auto)
        self.schedule = (
            schedule.value if isinstance(schedule, Schedule)
            else Schedule.from_string(str(schedule)).value
        )
        if precision is None:
            precision = get_option(None, Option.ServePrecision) or "full"
        self.precision = _bk.check_precision(precision)
        self.placement = (
            placement if placement is not None
            else PlacementPolicy.from_options(replicas=replicas)
        )
        # factor cache: default OFF (cache_from_options returns None
        # unless the env/options enable it) — the hot path then pays
        # exactly one `is None` branch per admission.  ``False`` is
        # the explicit off-switch: it wins over SLATE_TPU_FACTOR_CACHE
        # (a baseline/AB service must be able to opt out of the env)
        self.factor_cache = (
            None if factor_cache is False
            else factor_cache if factor_cache is not None
            else cache_from_options()
        )
        # device factor arena (fabric/): default OFF and meaningless
        # without the host cache — armed, solve-phase hits dispatch the
        # lane's device-resident factor buffer instead of re-uploading.
        # ``False`` is the explicit off-switch (wins over the env); the
        # fabric package is only imported when something arms it, so
        # the unarmed service is byte-identical to a build without it
        self.arena = None
        if factor_arena is not False and self.factor_cache is not None:
            if factor_arena is not None:
                self.arena = factor_arena
            elif os.environ.get("SLATE_TPU_FACTOR_ARENA") or get_option(
                None, Option.ServeFactorArena
            ):
                from ..fabric.arena import arena_from_options

                self.arena = arena_from_options()
        if self.placement.mesh:
            # fail FAST, and against the SAME device pool the sharded
            # lane will actually bind (parallel/spmd_core.grid_for uses
            # the process-global jax.devices(); the policy's explicit
            # device list only pins replicas): without this, every
            # sharded request would pay a failed spmd trace, trip the
            # breaker, and silently resolve via the single-device
            # direct fallback — an explicit "run this on the mesh"
            # deployment downgraded to metrics noise
            import jax

            ndev = len(jax.devices())
            if not _bk.mesh_fits(self.placement.mesh, ndev):
                from ..exceptions import DistributedException

                p, q = _bk.parse_mesh(self.placement.mesh)
                raise DistributedException(
                    f"serving mesh {self.placement.mesh} needs {p * q} "
                    f"devices, only {ndev} visible"
                )
        if faults_spec is None:
            faults_spec = get_option(None, Option.Faults) or ""
        # injection state is process-global (like metrics); a service
        # that armed it owns it and disarms on stop(), so a discarded
        # chaos service cannot keep poisoning later services
        self._owns_faults = bool(faults_spec)
        if faults_spec:
            faults.configure(faults_spec)
            faults.on()
        self._restore_on_start = restore_on_start
        self._phase = PHASE_COLD
        self._restore_result: Optional[Dict[str, int]] = None
        self._restore_thread: Optional[threading.Thread] = None
        # restore-stuck surfacing: past this age a still-restoring
        # phase is reported in health()["restore_stuck_s"] so an
        # orchestrator polling wait_ready(timeout=)/health() can tell
        # a wedged restore thread from a merely slow one
        self.restore_stuck_after_s = float(restore_stuck_after_s)
        self._restore_started: Optional[float] = None
        self._rng = random.Random(retry_seed)
        # the ONE service lock (workers, admission, health, drain all
        # meet here) — instrumented under SLATE_TPU_SYNC_CHECK, a plain
        # threading.Condition otherwise
        self._cond = sync.Condition(name="service.SolverService._cond")
        self._running = False
        self._stopped = False  # stop() called; submit() rejects until start()
        # the replicated tier: one lane per replica (replica i pins to
        # placement.device_for(i); the default single replica pins to
        # nothing — the pre-placement single-worker behavior), plus the
        # sharded lane when a mesh is configured
        self._replicas: List[_Replica] = [
            _Replica(str(i), self.placement.device_for(i))
            for i in range(self.placement.replicas)
        ]
        self._shard_rep: Optional[_Replica] = (
            _Replica("sharded") if self.placement.mesh else None
        )
        # the admission plane (tenancy + priority shedding + adaptive
        # batch window): None unless configured — the zero-overhead
        # contract is one `is None` branch per submit, plain deque
        # lanes, and byte-identical behavior
        self._admission = _adm.AdmissionControl.from_options(
            tenants=tenants, adaptive=adaptive,
            budget_s=latency_budget_s, ceiling_s=self.batch_window_s,
        )
        if self._admission is not None:
            for rep in self._lanes:
                rep.q = self._admission.new_queue()
        # the integrity plane (certification + quarantine + hedging):
        # None unless configured — the zero-overhead contract is one
        # `is None` branch per delivery and per sweep
        self._integrity = _integ.from_options(integrity)
        if self._integrity is not None:
            for rep in self._lanes:
                rep.score = self._integrity.new_score()
        self._hedge_last_sweep = 0.0  # guarded by: _cond
        self._restarts = 0
        self._recent_fail: Deque[float] = deque(maxlen=256)
        # latency-histogram labels this service has dispatched (the SLO
        # surface health() reports percentiles for)
        self._seen_labels: set = set()
        # elastic capacity plane (scale/): replica lifecycle state —
        # lane names are MONOTONIC ordinals (never reused: a reused
        # name would merge a dead lane's per-lane metric series with
        # its successor's), and removed/draining lanes keep a terminal
        # row so health() can tell scale-down from a crash
        self._next_replica = len(self._replicas)  # guarded by: _cond
        self._terminal: "OrderedDict[str, dict]" = OrderedDict()  # guarded by: _cond
        # the scaler itself (None unless configured — the zero-overhead
        # contract: with SLATE_TPU_SCALE unset the scale/ package is
        # never even imported and the hot path is byte-identical)
        self._scaler = None
        if _scale_policy_armed():
            from ..scale.controller import AutoScaler, policy_from_options

            policy = policy_from_options()
            if policy is not None:
                self._scaler = AutoScaler(self, policy)
        self._t_started = time.monotonic()
        if start:
            self.start()

    # -- lanes -------------------------------------------------------------

    @property
    def _lanes(self) -> List[_Replica]:
        return self._replicas + (
            [self._shard_rep] if self._shard_rep is not None else []
        )

    @property
    def _breakers(self) -> Dict[_bk.BucketKey, _bk.Breaker]:
        """Back-compat alias: the default replica's breaker table (the
        whole table of a single-replica service).  Returns the LIVE
        dict for test introspection — taking _cond around the fetch
        would not protect callers, who hold the alias unlocked; the
        chaos tests poke Breaker fields through it deliberately."""
        return self._replicas[0].breakers  # slate-lint: disable=lock-discipline

    def _gauge_queues_locked(self) -> int:
        total = 0
        mon = metrics.is_on()
        now = time.monotonic() if mon else 0.0
        for rep in self._lanes:
            d = len(rep.q)
            total += d
            metrics.gauge(rep.q_gauge, d)
            if mon:
                # age of the oldest queued request: queue depth alone
                # hides a stuck head-of-line request (satellite fix) —
                # t_submit is monotonic per request, min() is O(depth)
                # over a bounded queue
                metrics.gauge(
                    rep.oldest_gauge,
                    (now - min(r.t_submit for r in rep.q)) if rep.q else 0.0,
                )
        metrics.gauge("serve.queue_depth", total)
        return total

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "SolverService":
        with self._cond:
            if self._running:
                return self
            self._running = True
            self._stopped = False
        for rep in self._lanes:
            self._spawn_worker(rep)
        self._begin_restore()
        if self._scaler is not None:
            self._scaler.start()
        return self

    def _begin_restore(self) -> None:
        """Kick the one-time cold-start restore pass (phase cold ->
        restoring -> ready).  Runs once per service: a stop()/start()
        cycle keeps the already-ready phase (the executables are still
        in memory)."""
        want = (
            self._restore_on_start
            if self._restore_on_start is not None
            else self.cache.artifacts is not None
        )
        with self._cond:
            if self._phase != PHASE_COLD:
                return
            if not want:
                self._phase = PHASE_READY
                return
            self._phase = PHASE_RESTORING
            self._restore_started = time.monotonic()
            t = threading.Thread(
                target=self._run_restore, name="slate-serve-restore",
                daemon=True,
            )
            self._restore_thread = t
        t.start()

    def restore(self, verbose: bool = False, stop_check=None) -> Dict[str, int]:
        """Run the cache's artifact/manifest restore pass for THIS
        service's placement (every replica device primed, mesh-unfit
        entries skipped) — the ONE spelling of the restore plumbing,
        used by the start-time background pass and ``serve.restore()``
        alike.  Returns the cache's restore summary."""
        return self.cache.restore(
            batch_max=self.batch_max,
            stop_check=stop_check,
            devices=self.placement.replica_devices(),
            verbose=verbose,
        )

    def _run_restore(self) -> None:
        try:
            result = self.restore(stop_check=lambda: self._stopped)
        except Exception:  # noqa: BLE001 — a broken store must not block ready
            # distinct from the per-entry serve.restore_failed counter:
            # the whole pass died before/outside the entry loop.  The
            # sentinel keeps health()["restore"] distinguishable from
            # "restore was never configured" (None).
            metrics.inc("serve.restore_crashed")
            result = {
                "entries": 0, "restored": 0, "compiled": 0,
                "failed": 0, "skipped": 0, "crashed": True,
            }
        with self._cond:
            self._restore_result = result
            self._phase = PHASE_READY
            self._cond.notify_all()

    def wait_ready(self, timeout: Optional[float] = None) -> bool:
        """Block until the readiness phase reaches ``ready`` (True) or
        the timeout elapses (False) — the in-process analogue of an
        orchestrator polling ``health()["phase"]``.  A service built
        paused (``start=False``) and never started returns False
        immediately: nothing will ever advance its phase."""
        deadline = (
            time.monotonic() + timeout if timeout is not None else None
        )
        with self._cond:
            while self._phase != PHASE_READY:
                if not self._running and self._phase == PHASE_COLD:
                    return False  # never started; no restore coming
                left = (
                    deadline - time.monotonic()
                    if deadline is not None else 0.1
                )
                if deadline is not None and left <= 0:
                    return False
                self._cond.wait(min(left, 0.1) if left > 0 else 0.1)
            return True

    def warmup(
        self, path: Optional[str] = None, verbose: bool = False
    ) -> int:
        """Pre-compile the manifest's executables for THIS service's
        placement: every replica device is primed (so steady state is
        compile-free on all of them), and manifest entries whose mesh
        this process cannot realize are skipped.  Returns the number
        of executables compiled."""
        return self.cache.warmup(
            path=path, batch_max=self.batch_max,
            devices=self.placement.replica_devices(), verbose=verbose,
        )

    def _spawn_worker(self, rep: _Replica) -> None:
        t = threading.Thread(
            target=self._run_worker, args=(rep,),
            name=f"slate-serve-worker-{rep.name}", daemon=True,
        )
        with self._cond:
            rep.thread = t
        t.start()

    def stop(
        self,
        timeout: float = 10.0,
        drain: bool = False,
        drain_timeout: Optional[float] = None,
    ) -> None:
        """Stop the workers; unstarted/leftover requests resolve with
        Rejected (futures never hang).

        ``drain=True`` is the rolling-restart shape: admission closes
        immediately (new submits raise Rejected) but the workers keep
        running until every already-admitted request has resolved —
        bounded by ``drain_timeout`` (``Option.ServeDrainTimeout``
        when None) — so an orchestrator cycling replicas never fails
        in-flight futures.  Requests completed during the drain count
        ``serve.drained``; ones still pending at the bound count
        ``serve.drain_abandoned`` and resolve Rejected like any other
        leftover."""
        # the scaler first: a control loop adding/removing lanes while
        # the teardown below snapshots self._lanes would race it
        if self._scaler is not None:
            self._scaler.stop()
        if drain:
            if drain_timeout is None:
                from ..enums import Option
                from ..options import get_option

                drain_timeout = float(
                    get_option(None, Option.ServeDrainTimeout)
                )
            deadline_d = time.monotonic() + max(float(drain_timeout), 0.0)

            def _pending_locked() -> int:
                return sum(
                    len(rep.q) + len(rep.inflight) for rep in self._lanes
                )

            with self._cond:
                # close admission NOW: a drain that keeps admitting can
                # never finish.  _running stays True — the workers keep
                # popping and resolving what was already admitted.
                self._stopped = True
                start_pending = left = _pending_locked()
                while left and time.monotonic() < deadline_d:
                    self._cond.wait(0.02)
                    left = _pending_locked()
            metrics.inc("serve.drained", max(start_pending - left, 0))
            if left:
                metrics.inc("serve.drain_abandoned", left)
        with self._cond:
            self._running = False
            self._stopped = True
            leftovers: List[_Request] = []
            for rep in self._lanes:
                sync.guarded(rep, "q")
                leftovers.extend(rep.q)
                rep.q.clear()
            # zero the per-replica queue gauges too, or a metrics dump
            # after stop() shows phantom per-lane depth under a zero total
            self._gauge_queues_locked()
            self._cond.notify_all()
            threads = [rep.thread for rep in self._lanes]
        # ONE timeout budget for the whole teardown (not per thread):
        # an orchestrator's grace period is sized to `timeout`, not
        # timeout x (replicas + 1) with five wedged workers
        deadline = time.monotonic() + timeout
        for t in threads:
            if t is not None:
                t.join(max(0.0, deadline - time.monotonic()))
        with self._cond:
            for rep, t in zip(self._lanes, threads):
                if rep.thread is t:
                    rep.thread = None
        # the restore thread polls _stopped between entries; bounded
        # join so faults.reset() below never runs under a live pass
        with self._cond:
            rt = self._restore_thread
        if rt is not None and rt.is_alive():
            rt.join(max(0.0, deadline - time.monotonic()))
        for r in leftovers:
            _resolve_exc(r.future, Rejected("service stopped"), req=r)
        if self._owns_faults:
            faults.reset()
            self._owns_faults = False

    def __enter__(self) -> "SolverService":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False

    # -- elastic capacity (scale/) -----------------------------------------

    def _rehome_queue_locked(self, rep: _Replica) -> int:
        """Move every request queued on ``rep`` to surviving lanes
        (caller holds ``_cond``; ``rep`` is already out of
        ``self._replicas`` so the picker cannot choose it).  Returns
        the count moved."""
        pending = list(rep.q)
        if not pending:
            return 0
        rep.q.clear()
        for r in pending:
            tgt = self._pick_replica_locked(r.key)
            sync.guarded(tgt, "q")
            tgt.q.append(r)
        metrics.inc("scale.requests_rehomed", len(pending))
        metrics.gauge(rep.q_gauge, 0)
        self._gauge_queues_locked()
        self._cond.notify_all()
        return len(pending)

    def _prime_lane(self, rep: _Replica, plan=None) -> Dict[str, int]:
        """Artifact-first warm of one joining lane's device BEFORE it
        takes traffic — the scale-up half of the zero-steady-state-
        compiles contract (``ExecutableCache.prime``: export artifacts
        load where the store has them, per-device dispatch variants
        prime either way).  ``plan`` narrows the walk to a predictive
        :class:`~slate_tpu.scale.warmup_plan.WarmupPlan` (or a raw
        ``(key, batch)`` iterable); None warms the whole live
        manifest."""
        devices = [rep.device] if rep.device is not None else None
        entries = (
            plan.pairs() if hasattr(plan, "pairs")
            else list(plan) if plan is not None else None
        )

        def stop_check() -> bool:
            return self._stopped

        counts = self.cache.prime(
            entries, devices=devices, batch_max=self.batch_max,
            stop_check=stop_check, tag="scale_warm",
        )
        if metrics.is_on():
            for k in ("restored", "compiled", "failed", "skipped"):
                if counts.get(k):
                    metrics.inc(f"scale.prime_{k}", counts[k])
        return counts

    def add_replica(self, warm: bool = True, plan=None) -> str:
        """Bring one NEW serving lane live (elastic scale-up).

        The lane joins warm: its device is primed through the artifact
        store + the cache's partial bring-live walk before the worker
        spawns, so the lane's first steady-state request compiles
        nothing.  Lane names are monotonic ordinals and never reused —
        a reused name would splice a dead lane's per-lane metric
        series onto its successor's.  ``plan`` optionally narrows the
        warm walk (predictive warmup).  Returns the new lane's name.
        Raises RuntimeError when the service is not running."""
        with self._cond:
            if self._stopped or not self._running:
                raise RuntimeError("add_replica: service is not running")
            name = str(self._next_replica)
            self._next_replica += 1
            idx = len(self._replicas)
            # grow the placement domain first: device_for(idx) answers
            # against the NEW count (1 -> 2 starts real pinning)
            self.placement.set_replicas(idx + 1)
            device = self.placement.device_for(idx)
        rep = _Replica(name, device)
        warmed: Dict[str, int] = {}
        if warm:
            # outside _cond: priming compiles/loads executables —
            # seconds of work the serving lanes must not stall behind
            warmed = self._prime_lane(rep, plan)
        with self._cond:
            if self._stopped or not self._running:
                self.placement.set_replicas(len(self._replicas))
                raise RuntimeError(
                    "add_replica: service stopped while priming"
                )
            if self._admission is not None:
                rep.q = self._admission.new_queue()
            if self._integrity is not None:
                rep.score = self._integrity.new_score()
            self._replicas.append(rep)
            self.placement.set_replicas(len(self._replicas))
            fleet = len(self._replicas)
            self._cond.notify_all()
        self._spawn_worker(rep)
        metrics.inc("scale.replicas_added")
        metrics.gauge("scale.fleet", fleet)
        if spans.is_on():
            spans.event(
                "replica_added", lane=rep.lane,
                restored=warmed.get("restored", 0),
                compiled=warmed.get("compiled", 0),
            )
        return name

    def remove_replica(
        self, name: Optional[str] = None, drain_timeout: float = 30.0
    ) -> str:
        """Quiesce and remove one lane (elastic scale-down); default
        victim is the newest (highest-ordinal) lane.

        The lane leaves the admission pool immediately and its queue
        re-homes to surviving lanes (every admitted future stays owned
        by a live worker); the worker finishes any in-flight batch and
        exits via its drain branch, bounded by ``drain_timeout``.
        Lane-affine factor-cache entries then re-home to a survivor —
        repeat-A traffic keeps hitting instead of paying counted
        refactors.  The lane's health row does NOT vanish: it moves to
        the terminal table (state draining -> removed), so scale-down
        stays distinguishable from a crash.  Raises ValueError for the
        last lane or an unknown name."""
        with self._cond:
            if len(self._replicas) <= 1:
                raise ValueError(
                    "remove_replica: cannot remove the last lane"
                )
            if name is None:
                rep = self._replicas[-1]
            else:
                rep = next(
                    (r for r in self._replicas if r.name == name), None
                )
                if rep is None:
                    raise ValueError(
                        f"remove_replica: no lane named {name!r}"
                    )
            self._replicas.remove(rep)
            self.placement.set_replicas(len(self._replicas))
            sync.guarded(rep, "stopping")
            rep.stopping = True
            self._terminal[rep.name] = {
                "name": rep.name, "state": LANE_DRAINING,
                "device": (
                    str(rep.device) if rep.device is not None else None
                ),
                "dispatched": rep.dispatched,
                "restarts": rep.restarts,
            }
            moved = self._rehome_queue_locked(rep)
            self._cond.notify_all()
            t = rep.thread
            survivor = self._replicas[0]
        if spans.is_on():
            spans.event("drain", lane=rep.lane, rehomed=moved)
        if t is not None:
            t.join(max(float(drain_timeout), 0.0))
        # factor re-homing OUTSIDE _cond: FactorCache is self-locked
        # and LOCK_ORDER.json keeps service._cond out of its edges —
        # nesting here would mint a cond -> factor-cache edge for no
        # gain
        refactored = 0
        if self.factor_cache is not None:
            refactored = self.factor_cache.rehome(
                rep.name, survivor.name
            )
        if self.arena is not None:
            # device residency is lane-affine and the lane's device is
            # going away — free its HBM; survivors re-upload on next hit
            self.arena.drop_lane(rep.lane)
        with self._cond:
            # the worker exits through its drain branch; anything that
            # STILL landed here (a requeue racing the join bound)
            # moves too
            self._rehome_queue_locked(rep)
            if rep.thread is t:
                rep.thread = None
            row = self._terminal.get(rep.name, {"name": rep.name})
            row.update({
                "state": LANE_REMOVED, "dispatched": rep.dispatched,
                "restarts": rep.restarts,
                "factor_rehomed": refactored,
                "drain_timed_out": bool(t is not None and t.is_alive()),
            })
            self._terminal[rep.name] = row
            while len(self._terminal) > 64:  # bounded terminal table
                self._terminal.popitem(last=False)
            fleet = len(self._replicas)
        metrics.inc("scale.replicas_removed")
        metrics.inc(rep.removed_counter)
        metrics.gauge("scale.fleet", fleet)
        metrics.gauge(rep.q_gauge, 0)
        metrics.gauge(rep.oldest_gauge, 0.0)
        if refactored:
            metrics.inc("scale.factors_rehomed", refactored)
        if spans.is_on():
            spans.event(
                "replica_removed", lane=rep.lane,
                factor_rehomed=refactored,
            )
        return rep.name

    # -- admission ---------------------------------------------------------

    def submit(
        self,
        routine: str,
        A,
        B,
        deadline: Optional[float] = None,
        retries: int = 0,
        precision: Optional[str] = None,
        sharded: Optional[bool] = None,
        tenant: Optional[str] = None,
        priority=None,
        trace_id: Optional[str] = None,
    ) -> Future:
        """Enqueue one solve; returns a Future resolving to the cropped
        solution X (n x nrhs ndarray).

        ``deadline`` is seconds from now; ``retries`` re-runs the
        batched path (with backoff) on executable failure before
        falling back.  ``precision`` ("full"|"mixed") overrides the
        service-wide solve path for this request (gesv/posv only —
        gels always serves full precision).  ``sharded`` overrides the
        placement policy: True forces the spmd submesh (raises
        ValueError when none is configured or the routine has no
        sharded path), False forces the replicated tier, None routes
        by size (``shard_threshold``).  ``tenant``/``priority`` tag
        the request for the admission plane (``tenants=`` /
        ``SLATE_TPU_TENANTS``): tenant defaults to the anonymous
        ``"default"`` pool, priority ("high"|"normal"|"low", default
        "normal") orders overload shedding — both are no-ops on a
        service without the plane configured.  Raises
        :class:`Rejected` when the queue (or, tenancy on, this
        tenant's quota/queue share) is full, :class:`Shed` when the
        overload controller is refusing this priority class, and
        :class:`InvalidInput` on non-finite operands (before any
        queue/compile cost; disable with ``validate=False``).

        With ``aux/spans`` on (``SLATE_TPU_TRACE_RING``), the request
        gets a trace id and a root ``request`` span spanning admit ->
        deliver, with ``admit``/``queued``/``coalesce``/``execute`` |
        ``direct``/``backoff`` children and breaker instants — one
        complete chain per delivered request in the Chrome export.
        On a tenancy-enabled service the root span carries
        ``tenant``/``priority`` attrs.  ``trace_id`` adopts a caller's
        trace instead of minting one (the fleet worker passes the
        router's id so this host's spans join the request's
        cross-process chain); ignored with spans off."""
        if not spans.is_on():
            return self._submit(routine, A, B, deadline, retries,
                                precision, sharded, tenant, priority)
        tr = trace_id or spans.new_trace()
        root = spans.start("request", trace=tr, lane="client",
                           routine=routine)
        admit = spans.start("admit", trace=tr, parent=root, lane="client")
        try:
            fut = self._submit(routine, A, B, deadline, retries,
                               precision, sharded, tenant, priority,
                               _trace=tr, _root=root)
        except BaseException as e:
            # admission rejected this request (Rejected/InvalidInput/
            # shape errors): the chain closes here, outcome on both
            spans.end(admit, outcome=type(e).__name__)
            spans.end(root, outcome=type(e).__name__)
            raise
        spans.end(admit, outcome="enqueued")
        return fut

    def _submit(
        self,
        routine: str,
        A,
        B,
        deadline: Optional[float] = None,
        retries: int = 0,
        precision: Optional[str] = None,
        sharded: Optional[bool] = None,
        tenant: Optional[str] = None,
        priority=None,
        _trace: Optional[str] = None,
        _root: Optional[spans.Span] = None,
        _synthetic: bool = False,
    ) -> Future:
        adm = self._admission
        # one normalizer for both plane states: a tag the plane would
        # reject must fail identically with the plane off, or enabling
        # tenancy breaks previously-working client calls
        tname, prio = _adm.resolve_identity(tenant, priority)
        A = np.asarray(A)
        B = np.asarray(B)
        if B.ndim == 1:
            B = B[:, None]
        if A.ndim != 2 or B.ndim != 2 or A.shape[0] != B.shape[0]:
            raise ValueError(
                f"{routine}: bad shapes A{A.shape} B{B.shape}"
            )
        if adm is not None:
            # -- the admission plane (ONE branch when off) -------------
            # BEFORE the O(n^2) finiteness scan below: the whole point
            # of shedding is to refuse load without paying per-request
            # cost, so under overload a refused submit must cost O(1)
            if not _synthetic and adm.tenancy and faults.is_on():
                # tenant_flood: a synthetic burst of low-priority
                # requests from tenant "flood" cloning this request's
                # operands — the fairness machinery must absorb it.
                # Tenancy-gated (not just plane-gated): on an
                # adaptive-only plane tenant "flood" would inherit an
                # unlimited default quota and the burst would admit
                # wholesale, degrading the very traffic the drill is
                # meant to prove protected
                s = faults.fire("tenant_flood")
                if s is not None:
                    self._flood_burst(routine, A, B, s.burst)
            now = time.monotonic()
            # anti-latch: let an idle EWMA decay and de-escalate BEFORE
            # the shed decision — at shed level the refused requests
            # never execute, so without this no observation would ever
            # arrive to recover a service whose flood already stopped
            adm.tick(now)
            if adm.sheds(prio):
                # overload: refuse lowest-priority-first, typed — the
                # queue may have room, but admitting would melt the
                # SLO of what is already queued
                adm.tenant_event(tname, "shed")
                metrics.inc("serve.shed")
                if spans.is_on():
                    # a shed must stay O(1): even the span attrs are
                    # only built while tracing is armed
                    spans.event(
                        "shed", trace=_trace, lane="client", tenant=tname,
                        priority=_bk.priority_name(prio),
                        # deliberately lock-free level read: span attrs
                        # tolerate a stale value, and taking the
                        # admission lock on every shed would serialize
                        # the O(1) refusal path the plane exists for
                        level=adm.overload.level,  # slate-lint: disable=race-guarded-by
                    )
                raise Shed(
                    # deliberately lock-free: the error string tolerates
                    # a stale level (the shed verdict itself was taken
                    # under adm's own locking in sheds())
                    f"{routine}: overload level {adm.overload.level} "  # slate-lint: disable=race-guarded-by
                    f"is shedding {_bk.priority_name(prio)}-priority "
                    "traffic; back off or raise priority"
                ).with_context(
                    routine=routine, tenant=tname,
                    priority=_bk.priority_name(prio),
                )
        if self.validate:
            bad = (
                "A" if not np.all(np.isfinite(A))
                else "B" if not np.all(np.isfinite(B))
                else None
            )
            if bad is not None:
                metrics.inc("serve.invalid_input")
                raise InvalidInput(
                    f"{routine}: non-finite entries in {bad}"
                ).with_context(routine=routine)
        m, n = A.shape
        nrhs = B.shape[1]
        # validate even on the keyless direct path (underdetermined
        # gels) — a typo'd precision must fail loudly on every
        # routine, not just the bucketed ones
        prec = _bk.check_precision(
            precision if precision is not None else self.precision
        )
        # placement: "" = replicated tier, "PxQ" = the sharded lane
        mesh = self.placement.mesh_for(routine, n, sharded)
        if mesh and prec != "full":
            if sharded and precision is not None:
                # explicitly sharded AND explicitly mixed: contradictory
                raise ValueError(
                    f"{routine}: sharded serving is full-precision only"
                )
            if sharded:
                # explicit sharded under a mixed SERVICE default: the
                # caller asked for the mesh, not for mixed — serve the
                # request full-precision there
                prec = "full"
            else:
                mesh = ""  # size-routed mixed requests stay replicated
        if sharded and not mesh:
            raise ValueError(
                f"{routine}: sharded routing unavailable (no mesh "
                "configured, or the routine has no sharded path)"
            )
        # ABFT bucket routing: with the integrity plane's abft flag on,
        # eligible requests (gesv/posv, full precision, single device)
        # bucket under tag="abft" — the checksummed core family
        # (integrity/abft via cache._build_core).  Mutually exclusive
        # with the factor cache: factor-eligible traffic already rides
        # a 100%-residual-fenced hit path and a certified miss path,
        # so it keeps its machinery and the plain key.  BucketKey is
        # untouched — the checksum executables ride the existing
        # halving lattice under the existing tag field.
        use_abft = (
            self._integrity is not None and self._integrity.abft
            and self.factor_cache is None
            and routine in ("gesv", "posv")
            and prec == "full" and not mesh
        )
        key: Optional[_bk.BucketKey] = None
        if not (routine == "gels" and m < n):
            key = _bk.bucket_for(
                routine, m, n, nrhs, A.dtype,
                floor=self.dim_floor, nrhs_floor=self.nrhs_floor,
                schedule=self.schedule, precision=prec, mesh=mesh,
                tag=_abft.ABFT_TAG if use_abft else "",
            )
        # factor cache (ONE branch when disabled): fingerprint eligible
        # requests, classify hit (dispatch the trsm-only solve bucket
        # against the cached factor) vs miss (factor once via
        # _factor_direct, then cache)
        fc = self.factor_cache
        fp: Optional[str] = None
        hit: Optional[FactorEntry] = None
        full_key = key
        if (
            fc is not None and key is not None and not key.mesh
            and prec == "full" and routine in ("gesv", "posv", "gels")
        ):
            fp = matrix_fingerprint(
                A, routine, schedule=self.schedule, precision=prec
            )
            hit = fc.get(fp)
            if hit is not None:
                # the REQUEST's solve bucket, not the entry's: a same-A
                # request with a different nrhs bucket must dispatch at
                # its own shape (the factor pad depends only on n, so
                # the cached factor fits every sibling)
                key = full_key.solve_sibling()
            else:
                _fc_record("miss", fp=fp, label=key.label)
        req = _Request(
            routine=routine, key=key, A=A, B=B, m=m, n=n, nrhs=nrhs,
            deadline=(
                time.monotonic() + deadline if deadline is not None else None
            ),
            retries=int(retries),
            tenant=tname, priority=prio, tenanted=adm is not None,
            factor_fp=fp, factor_miss=bool(fp is not None and hit is None),
            trace=_trace, span=_root,
        )
        if _root is not None:
            spans.annotate(
                _root,
                bucket=key.label if key is not None else None,
                sharded=bool(key is not None and key.mesh),
            )
            if adm is not None:
                spans.annotate(
                    _root, tenant=tname,
                    priority=_bk.priority_name(prio),
                )
        with self._cond:
            if self._stopped:
                # a stopped service has no worker to ever resolve the
                # future (a paused-but-never-started one does: start());
                # admitting here would hang the sync wrappers
                metrics.inc("serve.rejected")
                raise Rejected(
                    "service stopped; configure() a new one"
                ).with_context(routine=routine)
            if sum(len(rep.q) for rep in self._lanes) >= self.max_queue:
                metrics.inc("serve.rejected")
                if adm is not None:
                    adm.tenant_event(tname, "rejected")
                raise Rejected(
                    f"queue full ({self.max_queue}); retry with backoff"
                ).with_context(
                    routine=routine,
                    tenant=tname if adm is not None else None,
                    priority=(
                        _bk.priority_name(prio) if adm is not None else None
                    ),
                )
            if adm is not None and adm.config_for(tname).share < 1.0:
                # per-tenant queue-share cap: a bursty tenant fills ITS
                # slice of the bounded queue and gets rejected there,
                # leaving the rest of the queue for its neighbors
                limit = adm.share_limit(tname, self.max_queue)
                depth_t = sum(
                    rep.q.depth(tname) for rep in self._lanes
                )
                if depth_t >= limit:
                    metrics.inc("serve.rejected")
                    metrics.inc("serve.rejected_share")
                    adm.tenant_event(tname, "rejected")
                    raise Rejected(
                        f"tenant {tname!r} queue share full "
                        f"({limit} of {self.max_queue}); retry with "
                        "backoff"
                    ).with_context(
                        routine=routine, tenant=tname,
                        priority=_bk.priority_name(prio),
                    )
            if adm is not None and not adm.quota_take(
                tname, time.monotonic()
            ):
                # the token bucket is the LAST admission check: a token
                # must only be consumed by a request that is actually
                # admitted — checking earlier would let rejections
                # caused by OTHERS (a full shared queue, a shape typo)
                # drain this tenant's quota, charging the victim for
                # its neighbor's flood.  The hot tenant still sheds its
                # OWN load first: quota rejection is per-tenant
                adm.tenant_event(tname, "rejected")
                metrics.inc("serve.rejected")
                metrics.inc("serve.rejected_quota")
                raise Rejected(
                    f"tenant {tname!r} token-bucket quota exhausted "
                    f"({adm.config_for(tname).rate:g}/s); retry with "
                    "backoff"
                ).with_context(
                    routine=routine, tenant=tname,
                    priority=_bk.priority_name(prio),
                )
            if key is not None and key.mesh:
                rep = self._shard_rep
            else:
                rep = self._pick_replica_locked(key)
                if hit is not None:
                    # factors are device-pinned: route the hit to the
                    # lane whose device already holds the factor's
                    # compiled variant — unless that lane's breaker for
                    # the solve bucket is cooling down, in which case
                    # the request SPILLS off the batched solve
                    # executable (counted) onto the direct factor path
                    # of the selected healthy lane, which still reuses
                    # the healthy factor (residual-fenced) or refactors
                    # if it is gone — never a dispatch into a
                    # known-sick path, never a wrong X
                    own = next(
                        (r for r in self._replicas
                         if r.name == hit.replica), None
                    )
                    if own is not None:
                        b = own.breakers.get(key)
                        own_load = len(own.q) + len(own.inflight)
                        alt_load = len(rep.q) + len(rep.inflight)
                        if b is not None and b.cooling_down(
                            time.monotonic(), self.breaker_cooldown_s
                        ):
                            now_cl = time.monotonic()
                            alt_b = rep.breakers.get(key)
                            if rep is not own and not (
                                alt_b is not None and alt_b.cooling_down(
                                    now_cl, self.breaker_cooldown_s
                                )
                            ):
                                # cross-lane hit: the factor is host
                                # numpy (and arena sharing is device->
                                # device), so the least-loaded healthy
                                # lane serves the SAME cached factor
                                # through its own warmed solve bucket —
                                # reuse survives the sick lane instead
                                # of demoting to a direct re-solve
                                _fc_record(
                                    "cross_lane_hit", fp=fp,
                                    label=key.label,
                                )
                            else:
                                # single lane (or every lane cooling):
                                # spill off the batched solve executable
                                # onto the direct factor path
                                _fc_record(
                                    "spill", fp=fp, label=full_key.label
                                )
                                req.key = key = full_key
                                req.factor_miss = True
                        elif (
                            self._scaler is not None
                            and own is not rep
                            and own_load > 2 * self.batch_max
                            and own_load >= 4 * (alt_load + 1)
                        ):
                            # elastic affinity spill: factor affinity
                            # would funnel a repeat-heavy burst onto the
                            # owning lane no matter how many lanes the
                            # capacity plane adds — a scale-up that
                            # nobody routes to is dead weight.  When the
                            # owner is drowning (queue+inflight past the
                            # batch window AND 4x the least-loaded lane)
                            # pay ONE counted refactor on the idle lane
                            # instead of queueing behind the backlog;
                            # the refactor re-pins the fingerprint there
                            # (fc.put in the worker), so affinity
                            # migrates and later hits follow.  Armed
                            # only with the scaler: the env-off service
                            # routes byte-identically.
                            _fc_record(
                                "spill", fp=fp, label=full_key.label
                            )
                            metrics.inc("scale.affinity_spills")
                            req.key = key = full_key
                            req.factor_miss = True
                        else:
                            rep = own
            if _root is not None:
                req.qspan = spans.start(
                    "queued", trace=_trace, parent=_root, lane=rep.lane,
                )
            sync.guarded(rep, "q")  # race-plane lockset probe (no-op off)
            rep.q.append(req)
            self._gauge_queues_locked()
            self._cond.notify_all()
        if key is not None and key.mesh:
            metrics.inc("serve.routed_sharded")
        elif key is not None:
            metrics.inc("serve.replicated_dispatch")
        metrics.inc("serve.requests")
        if adm is not None:
            adm.tenant_event(tname, "admitted")
        return req.future

    def _flood_burst(self, routine: str, A, B, count: int) -> None:
        """The ``tenant_flood`` fault site: inject ``count`` synthetic
        low-priority requests from tenant ``"flood"`` cloning the
        triggering request's operands.  Each rides the normal admission
        path (minus a recursive flood check), so the burst is exactly
        the abuse the fairness machinery exists for — quota rejections
        and overload sheds are counted where they happen, and admitted
        flood requests resolve like any others (nobody waits on them)."""
        for _ in range(max(int(count), 0)):
            try:
                self._submit(
                    routine, A, B, retries=0, tenant="flood",
                    priority="low", _synthetic=True,
                )
            except SlateError:
                pass  # shed/rejected — the point; counted at the raise

    def _pick_replica_locked(self, key: Optional[_bk.BucketKey]) -> _Replica:
        """Admission-side replica selection: least-loaded/round-robin
        via the placement policy, excluding replicas whose breaker for
        this bucket is OPEN while a healthy one exists."""
        if len(self._replicas) == 1:
            return self._replicas[0]
        loads = [len(r.q) + len(r.inflight) for r in self._replicas]
        open_fl = None
        if self._integrity is not None:
            # quarantine exclusion: a lane whose IntegrityScore is
            # quarantined AND still cooling down sheds NEW admissions
            # to healthy peers (capacity degrades, answers don't);
            # once the cooldown elapses the lane is selectable again
            # and its next certified delivery is the probe — the same
            # shape as the breaker's half-open window below
            now_q = time.monotonic()
            open_fl = [
                r.score is not None and r.score.excluded(now_q)
                for r in self._replicas
            ]
        if key is not None:
            # exclude a breaker-open replica only while its cooldown is
            # still running (Breaker.cooling_down — one definition with
            # try_half_open): once it elapses the lane must be
            # selectable again, or the half-open probe (driven by
            # _execute when a batch reaches the lane) could never fire
            # and the breaker would stay open forever behind healthy
            # peers.  Merged OR-wise with the quarantine flags above —
            # either exclusion steers admission off the lane.
            now = time.monotonic()
            br_fl = []
            for r in self._replicas:
                b = r.breakers.get(key)
                br_fl.append(
                    b is not None
                    and b.cooling_down(now, self.breaker_cooldown_s)
                )
            open_fl = (
                br_fl if open_fl is None
                else [a or b for a, b in zip(open_fl, br_fl)]
            )
        return self._replicas[self.placement.select_replica(loads, open_fl)]

    def queue_depth(self) -> int:
        with self._cond:
            return sum(len(rep.q) for rep in self._lanes)

    # -- health ------------------------------------------------------------

    def health(self) -> dict:
        """Liveness/readiness snapshot for external probes: total +
        per-replica queue depth vs limit, per-replica worker liveness,
        lifetime restarts, dispatch counts, breaker states and the age
        of each lane's oldest queued request, the recent failure rate
        (last 60 s over a bounded window), and — with metrics on — the
        SLO surface: per-bucket p50/p95/p99 total latency
        (``latency``) and the deadline-budget burn tiers
        (``slo_burn``) — with span tracing on, the flight recorder's
        eviction pressure (``trace_ring``: capacity/size/evicted/
        coverage window) — and, with devmon on (``SLATE_TPU_DEVMON=1``),
        the device surface: the per-bucket build-time cost/memory
        registry (``cost``: flops/bytes + argument/output/temp/peak
        bytes per batch point), each latency row's ``peak_bytes``
        (so one probe answers "slow because big" vs "slow because
        cold"), and per-device memory snapshots (``devices``; byte
        fields None on backends without ``memory_stats``).  Cheap
        enough to poll.  The legacy top-level
        ``breakers`` map merges the per-replica tables (worst state
        wins) so existing probes keep working; ``replicas`` (and
        ``sharded``, when a mesh is configured) carry the
        placement-aware detail."""
        now = time.monotonic()
        window_s = 60.0
        rank = {
            _bk.BREAKER_OPEN: 2, _bk.BREAKER_HALF_OPEN: 1,
            _bk.BREAKER_CLOSED: 0,
        }
        with self._cond:
            depth = sum(len(rep.q) for rep in self._lanes)
            alive = all(rep.alive() for rep in self._lanes)
            running = self._running
            restarts = self._restarts
            inflight = sum(len(rep.inflight) for rep in self._lanes)
            merged: Dict[str, str] = {}
            lanes = []
            for rep in self._lanes:
                states = {k.label: b.state for k, b in rep.breakers.items()}
                for lbl, st in states.items():
                    if rank[st] > rank.get(merged.get(lbl), -1):
                        merged[lbl] = st
                lanes.append({
                    "name": rep.name,
                    "state": LANE_LIVE,
                    "device": str(rep.device) if rep.device is not None
                    else None,
                    "queue_depth": len(rep.q),
                    "inflight": len(rep.inflight),
                    # a deep queue and a STUCK queue look identical in
                    # queue_depth; the head-of-line age disambiguates
                    "oldest_queued_s": (
                        (now - min(r.t_submit for r in rep.q))
                        if rep.q else 0.0
                    ),
                    "worker_alive": rep.alive(),
                    "restarts": rep.restarts,
                    "dispatched": rep.dispatched,
                    "breakers": states,
                })
            # terminal lanes (scale-down): draining/removed rows stay
            # in the table — a vanished row would make scale-down
            # indistinguishable from a crash
            terminal = [dict(row) for row in self._terminal.values()]
            recent = [t for t in self._recent_fail if now - t <= window_s]
            phase = self._phase
            restore_result = (
                dict(self._restore_result) if self._restore_result else None
            )
            seen_labels = sorted(self._seen_labels)
            tenant_depths: Optional[Dict[str, int]] = None
            if self._admission is not None:
                # merge the lanes' per-tenant depth maps (FairQueue
                # maintains them; no per-request scan under the lock)
                tenant_depths = {}
                for rep in self._lanes:
                    for t, d in rep.q.depths().items():
                        tenant_depths[t] = tenant_depths.get(t, 0) + d
        shard_lane = lanes.pop() if self._shard_rep is not None else None
        if shard_lane is not None:
            shard_lane["mesh"] = self.placement.mesh
        # terminal rows ride in the same per-replica table, normalized
        # to its shape (zero queue, dead worker) with their terminal
        # state — AFTER the shard pop so the pop stays positional
        for row in terminal:
            lanes.append({
                "queue_depth": 0, "inflight": 0, "oldest_queued_s": 0.0,
                "worker_alive": False, "breakers": {}, **row,
            })
        # the elastic capacity plane (None when off — the key is
        # always present, like integrity/tenants/admission)
        capacity = None
        if self._scaler is not None:
            capacity = self._scaler.describe()
            capacity["terminal_lanes"] = [r["name"] for r in terminal]
        # restore-stuck surfacing (satellite): a phase that has sat in
        # "restoring" past restore_stuck_after_s reports its age, so a
        # wait_ready(timeout=) caller that got False can tell a wedged
        # restore thread from a slow one with one more probe
        restore_stuck_s = None
        if phase == PHASE_RESTORING and self._restore_started is not None:
            age = now - self._restore_started
            if age > self.restore_stuck_after_s:
                restore_stuck_s = round(age, 3)
        # the integrity plane (None when off): policy + per-lane
        # quarantine scores (self-locked; read outside _cond)
        integrity = None
        if self._integrity is not None:
            scores = {
                rep.name: rep.score.snapshot(now)
                for rep in self._lanes if rep.score is not None
            }
            integrity = {
                "policy": self._integrity.describe(),
                "abft": self._integrity.abft,
                "replicas": scores,
                "quarantined": sorted(
                    n for n, s in scores.items()
                    if s["state"] == _integ.SCORE_QUARANTINED
                ),
            }
        # the SLO surface: per-bucket tail percentiles (total = admit ->
        # deliver) from the serve.latency histograms, plus the
        # deadline-budget burn counters — only populated while metrics
        # are on (health() stays cheap either way)
        latency: Dict[str, dict] = {}
        slo_burn: Dict[str, int] = {}
        if metrics.is_on():
            for lbl in seen_labels:
                s = metrics.hist_summary(f"serve.latency.{lbl}.total")
                if s:
                    latency[lbl] = {
                        k: s[k] for k in ("count", "p50", "p95", "p99")
                    }
            slo_burn = {
                name.rsplit(".", 1)[1]: int(v)
                for name, v in metrics.counters().items()
                if name.startswith("serve.slo_burn.")
            }
        # the device-telemetry surface (aux/devmon; both None when off
        # — one bool per probe, the registry deep-copy is never paid):
        # per-bucket build-time cost/memory registry, peak-bytes
        # threaded into the latency rows (one report answers "slow
        # because big" vs "slow because cold"), and a per-device
        # memory snapshot (bytes_in_use None on backends without
        # memory_stats — graceful, never a crash)
        # span-ring eviction pressure (None with tracing off): a soak
        # recording taken off a ring that has been silently evicting
        # is already truncated — surface capacity/evicted/coverage so
        # the gap is visible in the probe, not in a short load spec
        trace_ring = spans.pressure() if spans.is_on() else None
        cost = devices = None
        if devmon.is_on():
            cost = self.cache.costs_by_label() or None
            if cost:
                for lbl, ent in latency.items():
                    per = cost.get(lbl)
                    if per:
                        pk = max(
                            (c.get("peak_bytes") or 0)
                            for c in per.values()
                        )
                        if pk:
                            ent["peak_bytes"] = int(pk)
            devices = devmon.sample_devices()
        return {
            "ok": running and alive,
            "phase": phase,
            "ready": bool(running and alive and phase == PHASE_READY),
            "restore": restore_result,
            "restore_stuck_s": restore_stuck_s,
            "integrity": integrity,
            "running": running,
            "worker_alive": alive,
            "worker_restarts": restarts,
            "queue_depth": depth,
            "queue_limit": self.max_queue,
            "inflight": inflight,
            "breakers": merged,
            "open_buckets": sorted(
                lbl for lbl, s in merged.items() if s == _bk.BREAKER_OPEN
            ),
            "replicas": lanes,
            "sharded": shard_lane,
            "latency": latency,
            "slo_burn": slo_burn,
            "trace_ring": trace_ring,
            "cost": cost,
            "devices": devices,
            "factor_cache": (
                self.factor_cache.stats()
                if self.factor_cache is not None else None
            ),
            # the device factor arena (fabric/; None when unarmed):
            # per-lane residency + byte ledger vs budget
            "arena": (
                self.arena.stats() if self.arena is not None else None
            ),
            # the admission plane (both None when unconfigured):
            # per-tenant depth/quota/burn/shed/rejected, and the
            # controller state (overload level, shed classes, per-bucket
            # adaptive windows)
            "tenants": (
                self._admission.tenants_health(tenant_depths, now=now)
                if self._admission is not None else None
            ),
            "admission": (
                self._admission.snapshot()
                if self._admission is not None else None
            ),
            "capacity": capacity,
            "failures_60s": len(recent),
            "failure_rate_60s": len(recent) / window_s,
            "uptime_s": now - self._t_started,
        }

    def _note_failure(self) -> None:
        with self._cond:
            self._recent_fail.append(time.monotonic())

    # -- supervision -------------------------------------------------------

    def _run_worker(self, rep: _Replica) -> None:
        try:
            self._loop(rep)
        except BaseException as e:  # noqa: BLE001 — supervise ANY death
            self._supervise(rep, e)

    def _supervise(self, rep: _Replica, exc: BaseException) -> None:
        """Worker-death containment: re-enqueue the replica's in-flight
        requests that still have retry budget (with backoff), fail the
        rest fast with a typed error — no future ever hangs — and
        respawn the worker."""
        metrics.inc("serve.worker_restarts")
        with self._cond:
            sync.guarded(rep, "inflight")
            inflight, rep.inflight = rep.inflight, []
            rep.restarts += 1
            self._restarts += 1
            # a draining lane (scale-down) is never respawned — but its
            # retry-budgeted in-flight work still requeues: the lane's
            # queue is re-homed to survivors by remove_replica's final
            # sweep once this thread exits
            respawn = self._running and not rep.stopping
            requeue_ok = self._running
        self._note_failure()
        for r in inflight:
            if r.future.done():
                continue  # _execute resolved it before the death
            if requeue_ok and r.retries > 0:
                self._requeue_with_backoff(rep, r)
            else:
                # no worker will ever pop a re-enqueued request once
                # stop() has drained the queue — fail fast instead of
                # stranding the future
                _resolve_exc(
                    r.future,
                    SlateError(f"worker died mid-batch: {exc!r}"),
                    req=r,
                )
        if respawn:
            self._spawn_worker(rep)

    # -- worker ------------------------------------------------------------

    def _loop(self, rep: _Replica) -> None:
        while True:
            batch = self._next_batch(rep)
            if batch is None:
                return
            if not batch:
                continue
            with self._cond:
                sync.guarded(rep, "inflight")
                rep.inflight = batch
            faults.check("worker_death")  # in-flight: supervision must cover
            self._execute(rep, batch)
            with self._cond:
                sync.guarded(rep, "inflight")
                rep.inflight = []

    def _pop_eligible_locked(
        self, rep: _Replica, now: float
    ) -> Optional[_Request]:
        """Oldest request whose retry backoff (not_before) has elapsed
        — or, with the admission plane on, the weighted-fair choice
        across tenants (FairQueue's virtual-time schedule; FIFO within
        a tenant, and exactly FIFO with a single tenant)."""
        sync.guarded(rep, "q")  # race-plane lockset probe (no-op off)
        if self._admission is not None:
            return rep.q.pop_eligible(now)
        for i, r in enumerate(rep.q):
            if r.not_before <= now:
                del rep.q[i]
                return r
        return None

    def _next_batch(self, rep: _Replica) -> Optional[List[_Request]]:
        """Pop the oldest eligible request plus every same-key eligible
        request (up to batch_max).  None => stopped; [] => only expired
        requests were popped this round."""
        expired: List[_Request] = []
        with self._cond:
            first: Optional[_Request] = None
            while self._running and not rep.stopping:
                now = time.monotonic()
                # deadline sweep over the whole queue before eligibility:
                # a request that is backing off (not_before in the
                # future) must still be queued-cancelled the moment its
                # deadline passes, not after its backoff elapses
                if rep.q:
                    # remove-based (not rebuild): rep.q may be a plain
                    # deque or the admission plane's FairQueue — both
                    # support remove(), and the queue object (with its
                    # tenant bookkeeping) must survive the sweep
                    dead = [r for r in rep.q if r.expired()]
                    for r in dead:
                        rep.q.remove(r)
                    expired.extend(dead)
                if expired:
                    break  # cancel outside the lock, then come back
                if (
                    self._integrity is not None
                    and self._integrity.hedge_factor > 0
                    and len(self._replicas) > 1 and metrics.is_on()
                ):
                    # deadline-risk stragglers: any queued request
                    # whose age has passed the bucket's p99 gets a
                    # duplicate dispatched on another lane (sweeps ALL
                    # lanes from whichever worker runs first — a
                    # wedged lane cannot sweep its own queue)
                    self._hedge_stragglers_locked(now)
                first = self._pop_eligible_locked(rep, now)
                if first is not None:
                    break
                if rep.q:  # everything is backing off: sleep to the next
                    wake = min(r.not_before for r in rep.q) - now
                    self._cond.wait(min(max(wake, 0.001), 0.05))
                else:
                    self._cond.wait(0.05)
            if rep.stopping and self._running:
                # scale-down drain: this lane is leaving the fleet but
                # the SERVICE is still up — stragglers (a supervisor
                # requeue, a hedge clone landed after the drain sweep)
                # re-home to surviving lanes instead of failing
                self._rehome_queue_locked(rep)
                return None
            if not self._running:
                # resolve anything the failure path re-enqueued after
                # stop() drained the queue — futures must never strand
                leftovers = list(rep.q)
                rep.q.clear()
                for r in leftovers:
                    _resolve_exc(
                        r.future, Rejected("service stopped"), req=r
                    )
                return None
            self._gauge_queues_locked()
        if expired:
            for r in expired:
                self._miss_queued(r)
            return []
        if first.expired():
            self._miss_queued(first)
            return []
        if first.key is None:
            # keyless requests run direct
            return [first]
        if first.key.mesh and not (
            self.batch_max > 1
            and self.cache.is_live(first.key, self.batch_max)
        ):
            # the sharded lane coalesces only at batch points a warmup
            # has already realized: a cold batched spmd variant would
            # compile mid-traffic, breaking the steady-state contract.
            # When company is actually queued, record the batch point
            # in the manifest so the NEXT warmup brings the batched
            # variant live and coalescing activates from then on.
            if self.batch_max > 1:
                with self._cond:
                    company = any(
                        r.key == first.key
                        and r.factor_fp == first.factor_fp
                        for r in rep.q
                    )
                if company:
                    self.cache.ensure_manifest(
                        first.key, (1, self.batch_max)
                    )
                    metrics.inc("serve.mesh_batch_deferred")
            return [first]
        csp = spans.start("coalesce", trace=first.trace, parent=first.span,
                          lane=rep.lane) if first.trace is not None else None
        # the coalesce window: static configuration, or — admission
        # plane on — the bucket's AIMD window (ceiling batch_window_s)
        # times the overload shrink factor, so under pressure the lane
        # stops lingering for company
        win = (
            self.batch_window_s if self._admission is None
            else self._admission.window_for(first.key.label)
        )
        if self.batch_max > 1 and win > 0:
            with self._cond:
                now = time.monotonic()
                if not any(
                    r.key == first.key and r.factor_fp == first.factor_fp
                    and r.not_before <= now
                    for r in rep.q
                ):
                    self._cond.wait(win)
        batch = [first]
        with self._cond:
            now = time.monotonic()
            # take-based (not popleft-rebuild): front-to-back scan, so
            # the take set and order match the old loop on a deque —
            # and the queue object (FairQueue bookkeeping included)
            # survives.  Factor-cache requests additionally match on
            # the matrix fingerprint: a solve-phase batch shares ONE
            # factor operand, and a miss batch must not mix different
            # A's (factor_fp is None for everything else — plain
            # traffic coalesces exactly as before)
            take = [
                r for r in rep.q
                if r.key == first.key and r.factor_fp == first.factor_fp
                and r.not_before <= now
            ][: self.batch_max - 1]
            for r in take:
                rep.q.remove(r)
            batch.extend(take)
            self._gauge_queues_locked()
        spans.end(csp, coalesced=len(batch))
        live = []
        for r in batch:
            if r.expired():
                self._miss_queued(r)
            else:
                live.append(r)
        return live

    def _miss_queued(self, req: _Request) -> None:
        """Deadline passed while still queued: cancel, never start."""
        if req.is_hedge or req.future.done():
            # a hedge twin (or the original whose twin already
            # delivered): the LOGICAL request is accounted once, by
            # its primary — no deadline counters, no burn observation;
            # the resolution below is a no-op on a done future beyond
            # closing spans / group bookkeeping
            _resolve_exc(
                req.future,
                DeadlineExceeded(f"{req.routine}: hedge twin expired"),
                req=req,
            )
            return
        metrics.inc("serve.deadline_miss")
        metrics.inc("serve.deadline_miss_queued")
        if self._admission is not None:
            # a queued cancel IS an SLO exhaustion: feed the overload
            # controller its actual overrun (without this, a service
            # drowning in cancels would never see the burn and never
            # shed — deliveries are not the only melt signal)
            now = time.monotonic()
            self._admission.observe_finish(
                self._lat_label(req), req.tenant, req.priority,
                now - req.t_submit,
                req.deadline - req.t_submit
                if req.deadline is not None else None,
                now, trace=req.trace,
                windowed=req.key is not None and not req.key.mesh,
            )
        _resolve_exc(
            req.future,
            DeadlineExceeded(
                f"{req.routine} {req.m}x{req.n}: deadline passed after "
                f"{time.monotonic() - req.t_submit:.3f}s in queue"
            ),
            req=req,
        )

    def _miss_late(self, req: Optional[_Request] = None) -> None:
        """Finished past the deadline: result still delivered, counted.
        Hedge twins are skipped — as is a hedged PRIMARY whose twin
        already resolved the future (the client got a timely answer;
        only the losing lane was late) — so the logical request counts
        once, and only when the client actually waited."""
        if req is not None and (
            req.is_hedge
            or (req.hedge_group is not None and req.future.done())
        ):
            return
        metrics.inc("serve.deadline_miss")
        metrics.inc("serve.deadline_miss_late")

    # -- execution ---------------------------------------------------------

    def _breaker(self, rep: _Replica, key: _bk.BucketKey) -> _bk.Breaker:
        with self._cond:  # health() iterates breaker tables under the lock
            br = rep.breakers.get(key)
            if br is None:
                br = rep.breakers[key] = _bk.Breaker()
        return br

    def _execute(self, rep: _Replica, batch: List[_Request]) -> None:
        rep.dispatched += len(batch)
        metrics.inc(rep.dispatched_counter, len(batch))
        key = batch[0].key
        if metrics.is_on():
            # queued half of the latency split: admit -> FIRST dispatch
            # (coalesce window included — that wait IS queueing).  A
            # retried request is not re-observed: its second wait is
            # backoff, already visible in the serve.retry_backoff_s
            # timer and its backoff span — and one observation per
            # request keeps the queued count aligned with total's, the
            # subtraction premise of tools/latency_report.py
            now = time.monotonic()
            lbl = self._lat_label(batch[0])
            for r in batch:
                if r.attempt == 0:
                    metrics.observe_hist(
                        f"serve.latency.{lbl}.queued", now - r.t_submit
                    )
        if spans.is_on():
            for r in batch:
                spans.end(r.qspan, outcome="dispatched", replica=rep.name)
        if key is None:
            for r in batch:
                self._direct(r)
            return
        if batch[0].factor_miss:
            # factor-cache miss: factor ONCE through the drivers — the
            # factor is the product being cached, and the batched full
            # executable discards it — solve, cache, and register the
            # solve bucket in the warmup manifest for the hits to come
            for r in batch:
                self._factor_direct(rep, r)
            return
        br = self._breaker(rep, key)
        if br.state == _bk.BREAKER_OPEN:
            if br.try_half_open(time.monotonic(), self.breaker_cooldown_s):
                metrics.inc("serve.breaker_half_open")
                spans.event("breaker_half_open", trace=batch[0].trace,
                            lane=rep.lane, bucket=key.label)
            else:
                for r in batch:  # open: route direct until the cooldown
                    self._direct(r)
                return
        try:
            for r in batch:
                r.attempt += 1
            deliver, corrupt = self._execute_batched(rep, key, batch)
        except Exception as e:  # noqa: BLE001 — futures carry the error
            self._note_failure()
            if br.record_failure(time.monotonic(), self.degrade_after):
                metrics.inc("serve.breaker_open")
                metrics.inc(f"serve.replica.{rep.name}.breaker_open")
                metrics.inc("serve.degraded")  # legacy alias: open events
                spans.event("breaker_open", trace=batch[0].trace,
                            lane=rep.lane, bucket=key.label)
            retryable = [r for r in batch if r.retries > 0]
            rest = [r for r in batch if r.retries <= 0]
            for r in reversed(retryable):
                self._requeue_with_backoff(rep, r)
            for r in rest:
                self._direct(r, batched_error=e)
            return
        if corrupt:
            # delivered garbage is a batched-path failure even though
            # nothing raised: a deterministically-corrupt executable
            # must still open the breaker, and a half-open probe that
            # returned non-finite X must re-open, not close
            if br.record_failure(time.monotonic(), self.degrade_after):
                metrics.inc("serve.breaker_open")
                if metrics.is_on():
                    metrics.inc(f"serve.replica.{rep.name}.breaker_open")
                metrics.inc("serve.degraded")
                spans.event("breaker_open", trace=batch[0].trace,
                            lane=rep.lane, bucket=key.label, corrupt=True)
        elif corrupt is None:
            # the batched path never executed (a solve batch whose
            # factor was evicted in flight, demoted item-by-item):
            # neither success nor failure — a half-open probe stays
            # pending for the next real dispatch
            pass
        elif br.record_success():
            metrics.inc("serve.breaker_closed")  # half-open probe healed
            if metrics.is_on():
                metrics.inc(f"serve.replica.{rep.name}.breaker_closed")
            spans.event("breaker_closed", trace=batch[0].trace,
                        lane=rep.lane, bucket=key.label)
        # resolve futures only AFTER the breaker transition committed: a
        # client that wakes from .result() must observe consistent
        # breaker metrics / health() state
        for fn in deliver:
            fn()

    def _requeue_with_backoff(self, rep: _Replica, r: _Request) -> None:
        """Retry with exponential backoff + decorrelated jitter instead
        of an immediate re-enqueue (which would hammer a failing path
        in a tight loop).  The retry stays on ITS replica: the breaker
        accounting that failed is this lane's."""
        r.retries -= 1
        r.backoff_s = decorrelated_backoff(
            self._rng, r.backoff_s, self.retry_backoff_s,
            self.retry_backoff_cap_s,
        )
        r.not_before = time.monotonic() + r.backoff_s
        metrics.inc("serve.retries")
        metrics.observe("serve.retry_backoff_s", r.backoff_s)
        if r.trace is not None and spans.is_on():
            # the planned backoff window as a span: a slow request whose
            # time went into retry delay shows it on its own timeline
            # (the chaos span test asserts exactly this interval)
            t = spans.now()
            spans.record(
                "backoff", t, t + r.backoff_s, trace=r.trace,
                parent=r.span, lane=rep.lane,
                backoff_s=round(r.backoff_s, 6), retries_left=r.retries,
                attempt=r.attempt,
            )
        with self._cond:
            if r.span is not None and spans.is_on():
                r.qspan = spans.start(
                    "queued", trace=r.trace, parent=r.span, lane=rep.lane,
                    retry=True,
                )
            sync.guarded(rep, "q")
            rep.q.appendleft(r)
            self._cond.notify_all()

    def _execute_batched(
        self, rep: _Replica, key: _bk.BucketKey, batch: List[_Request]
    ):
        """Run one padded batch; returns ``(deliver, corrupt)``: the
        deferred per-item delivery thunks (resolutions happen in
        _execute, after the breaker bookkeeping, so clients never
        observe stale breaker state) and the count of corrupt-result
        items (a garbage batch is a breaker failure, not a success —
        nonzero ``info`` is NOT corruption: it is a numerical property
        of the input, no fault of the batched path)."""
        if key.phase == "solve":
            return self._execute_solve_batched(rep, key, batch)
        if key.mesh:
            # sharded buckets batch via the core's unrolled spmd loop
            # (never vmap over shard_map); the coalescer only builds a
            # multi-item batch when the batched variant is already
            # live, so bb > 1 here never compiles mid-traffic
            self.cache.ensure_manifest(key, (1,))
            bb = _bk.batch_bucket(len(batch), self.batch_max)
        else:
            self.cache.ensure_manifest(key, (1, self.batch_max))
            bb = _bk.batch_bucket(len(batch), self.batch_max)
        pads = [_bk.pad_request(key, r.A, r.B) for r in batch]
        while len(pads) < bb:  # repeat-pad to the fixed batch point
            pads.append(pads[0])
            metrics.inc("serve.batch_pad")
        A_b = np.stack([p[0] for p in pads])
        B_b = np.stack([p[1] for p in pads])
        t_exec = time.monotonic()
        t_exec_pc = spans.now() if spans.is_on() else 0.0
        if rep.device is not None:
            # replica pinning: the dispatch (and its per-device compiled
            # variant) lands on this replica's device
            X_b, info_b = self.cache.run(key, A_b, B_b, device=rep.device)
        else:
            X_b, info_b = self.cache.run(key, A_b, B_b)
        now = time.monotonic()
        exec_s = now - t_exec
        mon = metrics.is_on()
        if mon:
            with self._cond:
                self._seen_labels.add(key.label)
        if spans.is_on():
            t1_pc = spans.now()
            for r in batch:
                if r.trace is not None:
                    # one execute span per request (the batch interval;
                    # every delivered trace keeps a complete chain even
                    # when its wall time was shared with batch peers)
                    spans.record(
                        "execute", t_exec_pc, t1_pc, trace=r.trace,
                        parent=r.span, lane=rep.lane, bucket=key.label,
                        batch=len(batch),
                    )
        deliver = []
        corrupt = 0
        for i, r in enumerate(batch):
            if mon:
                # pad_waste is real arithmetic per delivered item: only
                # spend it while the registry is collecting
                metrics.inc(
                    "serve.bucket_pad_waste",
                    _bk.pad_waste(key, r.m, r.n, r.nrhs),
                )
                # execute/total halves of the split, per bucket AND per
                # replica — one observation per delivered request (a
                # batch peer shares the batch's execute wall; requests
                # that degrade to _direct get total there instead)
                metrics.observe_hist(
                    f"serve.latency.{key.label}.execute", exec_s
                )
            late = r.deadline is not None and now > r.deadline
            info = int(info_b[i]) if i < len(info_b) else 0
            if info > 0:
                # strictly positive: the drivers' numerical contract
                # (singular U, non-SPD) — deterministic, never retried.
                # Negative info is the ABFT in-trace bad flag, handled
                # with the certification below.
                if late:
                    self._miss_late(r)
                self._observe_total(rep, key.label, r, now)
                metrics.inc("serve.numerical_errors")
                deliver.append(functools.partial(
                    _resolve_exc, r.future,
                    NumericalError(f"{r.routine}: info={info}", info), r,
                ))
                continue
            abft_bad = info < 0
            X = _bk.crop_result(key, X_b[i], r.n, r.nrhs)
            mixed = key.precision == "mixed"
            if (self.validate or mixed) and not np.all(np.isfinite(X)):
                # a non-finite solution from finite inputs is a
                # corrupted executable result (the result_corrupt fault
                # site, a bad kernel, bit rot) — or, on a mixed-
                # precision bucket, the designed non-convergence signal
                # (serve_mixed_core NaN-poisons items the refinement
                # cannot certify; checked even with validate off, it is
                # the demotion contract): re-solve this item on the
                # full-precision direct driver rather than deliver
                # garbage (_direct does its own late-miss accounting —
                # counting here would double it).  With validate=True
                # admission proved the inputs finite; with it off,
                # check them now — garbage *inputs* are the client's
                # GIGO, not a bucket failure, and must not open the
                # breaker or masquerade as a refinement stall in the
                # demotion metrics.
                inputs_ok = self.validate or (
                    np.all(np.isfinite(r.A)) and np.all(np.isfinite(r.B))
                )
                if inputs_ok:
                    metrics.inc("serve.corrupt_result")
                    if mixed:
                        metrics.inc("serve.refine_demoted")
                    self._note_failure()
                    corrupt += 1
                deliver.append(functools.partial(self._direct, r))
                continue
            # delivery certification (integrity plane; ONE branch when
            # off): a finite-but-wrong X — the sdc_solve/sdc_factor
            # chaos sites, a flaky chip — must never reach the client.
            # ABFT buckets carry the in-trace verdict (abft_bad) for
            # free; the host-side certificate (checksum relation, or
            # the full residual fence for plain buckets) covers the
            # device->host leg.  A failed certificate re-executes,
            # hedged to a different replica when one exists.
            if self._integrity is not None and r.routine in (
                "gesv", "posv"
            ):
                if not self._certify(rep, r, X, key, abft_bad):
                    deliver.append(
                        functools.partial(self._cert_reexecute, rep, r)
                    )
                    continue
            elif abft_bad:
                # defense in depth: a flagged X from a checksummed
                # executable is never delivered even if the plane was
                # since disabled — re-solve direct
                deliver.append(functools.partial(self._direct, r))
                continue
            if late:
                self._miss_late(r)  # finished late; still delivered
            self._observe_total(rep, key.label, r, now)
            deliver.append(functools.partial(_resolve, r.future, X, r))
        if len(batch) > 1:
            metrics.inc("serve.batched")
            metrics.inc("serve.batched_requests", len(batch))
        return deliver, corrupt

    def _execute_solve_batched(
        self, rep: _Replica, key: _bk.BucketKey, batch: List[_Request]
    ):
        """The factor-cache hit path: run one trsm-only batch against
        the cached factor (same-fingerprint requests only — the
        coalescer guarantees it); returns ``(deliver, corrupt)`` with
        the same contract as :meth:`_execute_batched`.

        Every delivered item is residual-validated: a finite-but-wrong
        X (the ``factor_stale`` chaos site, a mis-applied update, bit
        rot in the cached factor) drops the factor and re-solves via
        the factor path — ``serve.factor_cache.stale`` — while a
        non-finite X keeps the full path's corrupt-result contract
        (breaker failure + direct re-solve; the executable, not the
        factor, is implicated).  An entry evicted or invalidated
        between admission and dispatch demotes every item to a counted
        refactor (``serve.factor_cache.refactor``) — never a wrong X.
        """
        fc = self.factor_cache
        entry = fc.get(batch[0].factor_fp) if fc is not None else None
        if entry is None:
            # corrupt=None: the solve executable never ran, so the
            # caller must NOT treat this as a batched-path success — a
            # half-open breaker's probe stays pending (record_success
            # here would close it without the suspect path ever
            # executing)
            deliver = []
            for r in batch:
                _fc_record("refactor", fp=r.factor_fp)
                deliver.append(functools.partial(self._factor_direct, rep, r))
            return deliver, None
        self.cache.ensure_manifest(key, (1, self.batch_max))
        bb = _bk.batch_bucket(len(batch), self.batch_max)
        ar = self.arena
        F = None
        if ar is not None and not faults.is_on():
            # device arena (fabric/): a resident buffer serves the
            # dispatch with zero host->device factor transfer.  Chaos
            # bypasses the arena entirely — the factor_stale perturb
            # below must reach the operand actually dispatched, and a
            # perturbed host copy must never be installed as resident
            F = ar.get(entry.fp, rep.lane, device=rep.device)
        if F is None:
            F = np.asarray(entry.factor)
            if faults.is_on():
                # factor_stale: serve a factor whose fingerprint
                # silently no longer matches A — finite, wrong, and
                # caught only by the residual validation below
                F = faults.perturb("factor_stale", F)
            elif ar is not None:
                # miss: upload once, dispatch the committed buffer —
                # the LAST upload this fingerprint pays on this lane
                F = ar.put(entry.fp, rep.lane, F, device=rep.device)
                if devmon.is_on():
                    ar.pressure(rep.lane, rep.device)
        Bs = []
        for r in batch:
            B = np.asarray(r.B)
            if entry.perm is not None:
                B = B[entry.perm]  # P B on host: the gather is free
            Bs.append(_bk.pad_rhs(B, key.m, key.nrhs))
        while len(Bs) < bb:  # repeat-pad to the fixed batch point
            Bs.append(Bs[0])
            metrics.inc("serve.batch_pad")
        # the factor rides UNBATCHED (the solve executable maps over B
        # only): no bb-sized host copy, no bb resident device copies
        B_b = np.stack(Bs)
        t_exec = time.monotonic()
        t_exec_pc = spans.now() if spans.is_on() else 0.0
        if rep.device is not None:
            X_b, _info_b = self.cache.run(key, F, B_b, device=rep.device)
        else:
            X_b, _info_b = self.cache.run(key, F, B_b)
        now = time.monotonic()
        exec_s = now - t_exec
        mon = metrics.is_on()
        if mon:
            with self._cond:
                self._seen_labels.add(key.label)
        if spans.is_on():
            t1_pc = spans.now()
            for r in batch:
                if r.trace is not None:
                    spans.record(
                        "execute", t_exec_pc, t1_pc, trace=r.trace,
                        parent=r.span, lane=rep.lane, bucket=key.label,
                        batch=len(batch), factor_hit=True,
                    )
        deliver = []
        corrupt = 0
        stale = False
        for i, r in enumerate(batch):
            if mon:
                # pad_waste is real arithmetic per delivered item: only
                # spend it while the registry is collecting
                metrics.inc(
                    "serve.bucket_pad_waste",
                    _bk.pad_waste(key, r.m, r.n, r.nrhs),
                )
                # the trsm-only half of the latency story: the solve
                # bucket label carries the ".solve" suffix, so these
                # land in serve.latency.<bucket>.solve.{execute,total}
                metrics.observe_hist(
                    f"serve.latency.{key.label}.execute", exec_s
                )
            X = _bk.crop_result(key, X_b[i], r.n, r.nrhs)
            late = r.deadline is not None and now > r.deadline
            if not np.all(np.isfinite(X)):
                # corrupted executable result (result_corrupt site /
                # bad kernel): identical contract to the full path —
                # breaker failure + direct re-solve; the cached factor
                # is not implicated
                inputs_ok = self.validate or (
                    np.all(np.isfinite(r.A)) and np.all(np.isfinite(r.B))
                )
                if inputs_ok:
                    metrics.inc("serve.corrupt_result")
                    self._note_failure()
                    corrupt += 1
                deliver.append(functools.partial(self._direct, r))
                continue
            if not residual_ok(r.A, r.B, X, routine=r.routine):
                # finite but WRONG: the factor no longer matches A —
                # drop it and re-solve through the factor path (which
                # refactors and re-caches a fresh entry)
                _fc_record("stale", fp=entry.fp, label=entry.key.label)
                stale = True
                deliver.append(functools.partial(self._factor_direct, rep, r))
                continue
            _fc_record("hit", fp=entry.fp, label=entry.key.label)
            if r.span is not None and spans.is_on():
                spans.annotate(r.span, factor_hit=True)
            if late:
                self._miss_late(r)
            self._observe_total(rep, key.label, r, now)
            deliver.append(functools.partial(_resolve, r.future, X, r))
        if stale and fc is not None:
            fc.invalidate(entry.fp)
            if ar is not None:
                # the device copies go with the host entry: a stale
                # factor must not keep serving from HBM residency
                ar.drop(entry.fp)
        if len(batch) > 1:
            metrics.inc("serve.batched")
            metrics.inc("serve.batched_requests", len(batch))
        return deliver, corrupt

    def _factor_direct(self, rep: Optional[_Replica], req: _Request) -> None:
        """The factor-cache miss/refactor path: one direct driver
        factorization whose factor is CAPTURED (padded to the bucket,
        cached, its solve bucket registered in the warmup manifest) and
        whose solve is the trsm-only sweep from those factors — the
        request pays O(n^3) exactly once per distinct A.  Re-checks the
        cache first: in a same-A burst the first member factors and the
        rest find the entry mid-flight (counted hits, trsm-only)."""
        fc = self.factor_cache
        fp = req.factor_fp
        fkey = req.key
        if fkey is not None and fkey.phase != "full":
            import dataclasses

            fkey = dataclasses.replace(fkey, phase="full")
        entry = fc.get(fp) if (fc is not None and fp) else None
        cm = (
            spans.span("factor", trace=req.trace, parent=req.span,
                       routine=req.routine)
            if req.trace is not None and spans.is_on()
            else contextlib.nullcontext()
        )
        try:
            with cm:
                with metrics.phase(f"serve.factor.{req.routine}"):
                    faults.sleep("latency")
                    faults.check("execute")
                    X = None
                    if entry is not None:
                        # the factor landed while this request was
                        # queued (same-A burst) or the request spilled
                        # here off a cooling lane: trsm-only, a hit —
                        # under the SAME residual fence as the batched
                        # hit path ("never a wrong X" admits no side
                        # door; a mis-keyed update would slip through
                        # here otherwise)
                        X = solve_from_factor(entry, req.B)
                        if residual_ok(
                            req.A, req.B, X, routine=req.routine
                        ):
                            _fc_record("hit", fp=fp, label=entry.key.label)
                            spans.annotate(factor_hit=True)
                        else:
                            _fc_record(
                                "stale", fp=fp, label=entry.key.label
                            )
                            fc.invalidate(fp)
                            if self.arena is not None:
                                self.arena.drop(fp)
                            entry, X = None, None
                    if entry is None:
                        if req.routine == "gels":
                            # tall QR: the cached factor is the packed
                            # V/R + compact-WY T pack of the bucket-
                            # padded A (factor_cache.gels_factor_pack)
                            # — the exact solve-executable operand
                            factor = gels_factor_pack(
                                req.A, fkey, schedule=self.schedule
                            )
                            factor = faults.perturb("sdc_factor", factor)
                            perm = None
                        else:
                            raw, perm = factor_only(
                                req.routine, req.A, schedule=self.schedule
                            )
                            # sdc_factor: silent corruption of the
                            # freshly computed factor (finite wrong
                            # value) — this request's X goes wrong
                            # through the solve below (delivery
                            # certification must catch it), and the
                            # poisoned entry is CACHED, so later hits
                            # must fall to the residual fence (counted
                            # stale -> invalidate -> refactor)
                            raw = faults.perturb("sdc_factor", raw)
                            factor = _bk.pad_square(raw, fkey.n)
                        entry = FactorEntry(
                            fp=fp, routine=req.routine, key=fkey,
                            factor=factor, perm=perm,
                            n=req.n,
                        )
                        if fc is not None and fp:
                            fc.put(
                                entry,
                                replica=rep.name if rep is not None else None,
                            )
                            # the hits to come ride the warmed manifest:
                            # register the solve bucket NOW so the next
                            # warmup()/restore() precompiles it
                            self.cache.ensure_manifest(
                                entry.solve_key, (1, self.batch_max)
                            )
                        X = solve_from_factor(entry, req.B)
                spans.annotate(outcome="ok")
        except Exception as e:  # noqa: BLE001 — futures carry the error
            _resolve_exc(req.future, e, req=req)
            return
        # delivery certification (ONE branch when the plane is off):
        # the factor path is where sdc_factor bites — a silently
        # corrupted fresh factor yields a finite wrong X that no
        # finiteness fence sees
        if self._integrity is not None and not self._certify(
            rep, req, X, req.key, False
        ):
            self._cert_reexecute(rep, req)
            return
        now = time.monotonic()
        if req.deadline is not None and now > req.deadline:
            self._miss_late(req)
        # observe total under the DISPATCH key's label (req.key:
        # the full label for misses, the .solve label for items
        # demoted off a solve batch) so it pairs with the queued
        # observation _execute made under the same label — the
        # subtraction premise of tools/latency_report.py
        lbl = self._lat_label(req)
        if metrics.is_on():
            with self._cond:
                self._seen_labels.add(lbl)
        self._observe_total(rep, lbl, req, now)
        _resolve(req.future, X, req)

    @staticmethod
    def _lat_label(req: _Request) -> str:
        """Histogram label of a request: the bucket label, or
        ``<routine>.direct`` for keyless (direct-only) requests."""
        return (
            req.key.label if req.key is not None
            else f"{req.routine}.direct"
        )

    def _observe_total(self, rep: Optional[_Replica], label: str,
                       req: _Request, now: float) -> None:
        """Total (admit -> deliver) latency into the per-bucket and
        per-replica histograms, plus the deadline-budget burn counters
        (``serve.slo_burn.*``) — and, admission plane on, the control
        loop (overload EWMA + the bucket's AIMD window).  Called on
        every delivery; metrics are gated here, the control loop runs
        with or without them.  Hedge twins never observe — exactly one
        total (the primary's) per logical request, preserving the
        queued/total count alignment latency_report subtracts on.  A
        hedged primary whose twin already resolved the future is
        skipped too: the client-visible latency was the twin's, and
        feeding the loser's (slower) wall into the histograms and the
        burn controller would erase hedging's entire effect on
        recorded p99 — or worse, shove the overload controller into
        shedding over latencies nobody experienced."""
        if req.is_hedge or (
            req.hedge_group is not None and req.future.done()
        ):
            return
        total = now - req.t_submit
        if metrics.is_on():
            metrics.observe_hist(f"serve.latency.{label}.total", total)
            if rep is not None:
                metrics.observe_hist(rep.lat_hist, total)
            if req.deadline is not None:
                budget = req.deadline - req.t_submit
                if budget > 0:
                    # each delivered deadline request lands in exactly
                    # one burn tier: <=50% is healthy headroom, the
                    # rest is the SLO melting in slow motion
                    # (exhausted == delivered late, the
                    # deadline_miss_late companion)
                    burn = total / budget
                    metrics.inc("serve.slo_burn.requests")
                    if burn > 1.0:
                        metrics.inc("serve.slo_burn.exhausted")
                    elif burn > 0.8:
                        metrics.inc("serve.slo_burn.over_80")
                    elif burn > 0.5:
                        metrics.inc("serve.slo_burn.over_50")
        if self._admission is not None:
            # close the control loop: per-tenant burn/latency, the
            # overload EWMA, and the bucket's AIMD window decision —
            # the window only for coalescible buckets (keyless/direct
            # and sharded requests never read one)
            self._admission.observe_finish(
                label, req.tenant, req.priority, total,
                req.deadline - req.t_submit
                if req.deadline is not None else None,
                now, trace=req.trace,
                lane=rep.lane if rep is not None else None,
                windowed=req.key is not None and not req.key.mesh,
            )

    def _direct(self, req: _Request, batched_error: Optional[Exception] = None) -> None:
        if req.key is not None:
            metrics.inc("serve.fallbacks")  # degradation, not routing
        else:
            metrics.inc("serve.direct_only")  # e.g. underdetermined gels
        # a context-managed span (not start/end): it is this thread's
        # spans.current() while the driver runs, so annotations from
        # inside (e.g. refine iteration counts) land on it
        cm = (
            spans.span("direct", trace=req.trace, parent=req.span,
                       routine=req.routine)
            if req.trace is not None and spans.is_on()
            else contextlib.nullcontext()
        )
        try:
            with cm:
                with metrics.phase(f"serve.direct.{req.routine}"):
                    X = direct_call(req.routine, req.A, req.B)
                spans.annotate(outcome="ok")
        except Exception as e:  # noqa: BLE001 — futures carry the error
            # the span closed with outcome=<exception type> at __exit__
            if batched_error is not None:
                e.__context__ = batched_error
            _resolve_exc(req.future, e, req=req)
            return
        # delivery certification (ONE branch when the plane is off):
        # the direct lane is hardware like any other — sdc_solve fires
        # here too, and the re-execution fallback must re-certify
        if (
            self._integrity is not None
            and req.routine in ("gesv", "posv")
            and not self._certify(None, req, X, req.key, False)
        ):
            self._cert_reexecute(None, req)
            return
        now = time.monotonic()
        if req.deadline is not None and now > req.deadline:
            self._miss_late(req)
        lbl = self._lat_label(req)
        if metrics.is_on():
            with self._cond:
                self._seen_labels.add(lbl)
        self._observe_total(None, lbl, req, now)
        _resolve(req.future, X, req)

    # -- integrity: certification, quarantine, hedged re-execution ---------

    def _certify(
        self,
        rep: Optional[_Replica],
        req: _Request,
        X: np.ndarray,
        key: Optional[_bk.BucketKey],
        abft_bad: bool,
    ) -> bool:
        """One delivery's certificate (integrity plane ON — the caller
        holds the ``is None`` branch).  Returns True to deliver, False
        on a failed certificate (the caller re-executes; a wrong X
        never reaches the client).

        Verdict source: the in-trace ABFT bad flag when the bucket was
        built with checksums (free), plus — per the policy's
        ``full``/``sample=p`` gate — a host-side check covering the
        device->host leg: the O(n^2) checksum relation for ABFT
        buckets, the full residual fence otherwise.  Every verdict
        feeds the lane's :class:`IntegrityScore`; the quarantine /
        recovery transitions it causes are counted per replica."""
        integ = self._integrity
        if abft_bad:
            ok = False
        elif (
            req.cert_fails
            or (rep is not None and rep.score is not None
                and rep.score.suspect())
            or integ.should_check()
        ):
            # always certified regardless of the sampling rate: a
            # RE-EXECUTION ("a failed certificate never reaches the
            # client" admits no unsampled retry delivery — and the
            # recovered/hedge.won accounting depends on the verdict)
            # and any delivery from a QUARANTINED lane (the
            # post-cooldown probe must be the next delivery, not the
            # next sampled one ~1/p deliveries of wrong answers later)
            is_abft = key is not None and key.tag == _abft.ABFT_TAG
            A = _cert_operand(req)
            ok = (
                _abft.checksum_certificate(A, req.B, X) if is_abft
                else residual_ok(A, req.B, X, routine=req.routine)
            )
        else:
            return True  # unsampled delivery: no verdict, no score move
        mon = metrics.is_on()
        metrics.inc("serve.integrity.checked")
        if rep is not None and rep.score is not None:
            ev = rep.score.observe(ok, time.monotonic())
            if ev == "quarantined":
                metrics.inc("serve.integrity.quarantined")
                if mon:
                    metrics.inc(rep.quar_counter)
                if spans.is_on():
                    spans.event(
                        "quarantined", trace=req.trace, lane=rep.lane,
                        replica=rep.name,
                    )
            elif ev == "recovered":
                metrics.inc("serve.integrity.unquarantined")
                if mon:
                    metrics.inc(rep.unquar_counter)
                if spans.is_on():
                    spans.event(
                        "unquarantined", trace=req.trace, lane=rep.lane,
                        replica=rep.name,
                    )
        if ok:
            if req.cert_fails:
                # a previously-failed request delivered a PASSING
                # result: the re-execution (hedged or direct) won
                metrics.inc("serve.integrity.recovered")
                if req.reexec_hedged:
                    metrics.inc("serve.hedge.won")
                    req.reexec_hedged = False
            return True
        metrics.inc("serve.integrity.fail")
        self._note_failure()
        if spans.is_on() and req.trace is not None:
            spans.event(
                "cert_fail", trace=req.trace,
                lane=rep.lane if rep is not None else "direct",
                bucket=key.label if key is not None else None,
                abft=abft_bad,
            )
        return False

    def _cert_reexecute(
        self, rep: Optional[_Replica], req: _Request
    ) -> None:
        """A failed certificate never reaches the client: re-execute.

        While retry budget lasts (``policy.cert_retry_max``) the
        request is HEDGED to a different replica — Dean & Barroso's
        move: a suspect lane's work re-runs elsewhere, not in place
        (``serve.hedge.sent``; the certified re-delivery counts
        ``serve.integrity.recovered`` + ``serve.hedge.won``).  With no
        other lane it re-runs on the direct driver (a different code
        path off the suspect executable).  Budget exhausted: one
        last-resort direct solve behind the full residual fence —
        delivered only when it passes, else a typed NumericalError
        (``serve.integrity.abandoned``; never a silent wrong X)."""
        integ = self._integrity
        req.cert_fails += 1
        if req.future.done():
            # a hedge twin already delivered this request: the failed
            # result is discarded — no re-execution ladder for a
            # future nobody can consume (the resolver still closes
            # spans and the group bookkeeping)
            _resolve_exc(
                req.future,
                NumericalError(
                    f"{req.routine}: certificate-failed result "
                    "discarded; hedge twin already delivered"
                ),
                req=req,
            )
            return
        if req.is_hedge:
            # a raced straggler CLONE: never re-execute it — its
            # primary keeps the full retry ladder and may still
            # deliver; this member just failed (suppressed by the
            # group unless the primary fails too)
            _resolve_exc(
                req.future,
                NumericalError(
                    f"{req.routine}: hedge result failed certification"
                ),
                req=req,
            )
            return
        if req.cert_fails <= integ.cert_retry_max:
            other = None
            if len(self._replicas) > 1:
                excluded = self._quarantined_names()
                with self._cond:
                    if self._stopped or not self._running:
                        # a lane re-enqueued onto after stop()'s
                        # leftover harvest has no worker to ever pop
                        # it — fall through to the in-place direct
                        # re-execution below, which resolves the
                        # future on THIS thread (futures never hang)
                        other = None
                    else:
                        other = self._least_loaded_other_locked(
                            rep, excluded
                        )
                    if other is not None:
                        metrics.inc("serve.hedge.sent")
                        req.reexec_hedged = True
                        req.not_before = 0.0
                        # the queued histogram observed this request at
                        # its FIRST dispatch; a factor-path request
                        # reaches here with attempt still 0, and the
                        # re-enqueue must not observe it twice
                        req.attempt = max(req.attempt, 1)
                        if req.span is not None and spans.is_on():
                            req.qspan = spans.start(
                                "queued", trace=req.trace,
                                parent=req.span, lane=other.lane,
                                hedge=True,
                            )
                        sync.guarded(other, "q")
                        other.q.appendleft(req)
                        self._gauge_queues_locked()
                        self._cond.notify_all()
            if other is not None:
                if spans.is_on() and req.trace is not None:
                    spans.event(
                        "hedge", trace=req.trace, lane=other.lane,
                        reason="certificate", attempt=req.cert_fails,
                    )
                return
            # single lane: the direct driver IS the different path off
            # the suspect executable; _direct re-certifies (plane on)
            self._direct(req)
            return
        # budget exhausted: last-resort direct solve, residual-fenced
        try:
            with metrics.phase(f"serve.direct.{req.routine}"):
                X = direct_call(req.routine, req.A, req.B)
        except Exception as e:  # noqa: BLE001 — futures carry the error
            _resolve_exc(req.future, e, req=req)
            return
        if residual_ok(_cert_operand(req), req.B, X, routine=req.routine):
            metrics.inc("serve.integrity.recovered")
            if req.reexec_hedged:
                metrics.inc("serve.hedge.won")
                req.reexec_hedged = False
            now = time.monotonic()
            if req.deadline is not None and now > req.deadline:
                self._miss_late(req)
            self._observe_total(rep, self._lat_label(req), req, now)
            _resolve(req.future, X, req)
            return
        # the last-resort fence caught corruption too: count it as a
        # detection (serve.integrity.fail) alongside the refusal, or
        # integrity_report's injected-vs-detected escape check would
        # read a correctly-refused injection as a delivered escape
        metrics.inc("serve.integrity.fail")
        metrics.inc("serve.integrity.abandoned")
        _resolve_exc(
            req.future,
            NumericalError(
                f"{req.routine}: result failed integrity certification "
                f"{req.cert_fails}x across re-executions; refusing to "
                "deliver an uncertified X"
            ),
            req=req,
        )

    def _quarantined_names(self) -> set:
        """Names of lanes currently quarantine-excluded (scores are
        self-locked leaves: safe with or without ``_cond`` held, never
        the other way around)."""
        now = time.monotonic()
        return {
            r.name for r in self._replicas
            if r.score is not None and r.score.excluded(now)
        }

    def _least_loaded_other_locked(
        self, rep: Optional[_Replica], excluded: set
    ) -> Optional[_Replica]:
        """Least-loaded replica other than ``rep``, preferring lanes
        NOT in ``excluded`` (quarantined) and falling back to one that
        is — re-executing somewhere beats nowhere.  The ONE spelling
        of hedge-target selection, shared by the certificate
        re-execution and the straggler sweep."""
        best = best_ex = None
        load_b = load_ex = 0
        for r in self._replicas:
            if r is rep:
                continue
            load = len(r.q) + len(r.inflight)
            if r.name in excluded:
                if best_ex is None or load < load_ex:
                    best_ex, load_ex = r, load
            elif best is None or load < load_b:
                best, load_b = r, load
        return best if best is not None else best_ex

    def _hedge_stragglers_locked(self, now: float) -> None:
        """Deadline-risk straggler hedging (Dean & Barroso): any queued
        request whose age has passed ``hedge_factor`` x its bucket's
        p99 (the PR9 latency histograms) gets a DUPLICATE dispatched on
        the least-loaded healthy other lane — first correct result
        wins the shared Future, the loser counts
        ``serve.hedge.wasted``.  Swept under ``_cond`` from every
        worker's pop loop across ALL lanes (a wedged lane cannot sweep
        its own queue).  Caller guarantees the plane is on, >= 2
        replicas, and metrics armed (the p99 source)."""
        integ = self._integrity
        # rate-limit: every worker's pop/wait loop reaches here (up to
        # every 50 ms each), and the sweep is O(total queue depth) of
        # lock-held work plus a percentile per label — bound it to one
        # sweep per hedge_min_age_s across the whole service (finer
        # sweeps could not change any request's verdict anyway)
        if now - self._hedge_last_sweep < max(integ.hedge_min_age_s, 0.01):
            return
        self._hedge_last_sweep = now
        excluded: Optional[set] = None
        p99s: dict = {}  # per-label memo: one histogram scan per sweep
        hedged = False
        for rep in self._replicas:
            for r in list(rep.q):
                if (
                    r.is_hedge or r.hedge_group is not None
                    or r.key is None or r.key.mesh or r.attempt
                    or r.cert_fails
                ):
                    continue
                age = now - r.t_submit
                if age < integ.hedge_min_age_s:
                    continue
                lbl = r.key.label
                if lbl not in p99s:
                    p99s[lbl] = metrics.percentile(
                        f"serve.latency.{lbl}.total", 99
                    )
                p99 = p99s[lbl]
                if p99 is None or age < integ.hedge_factor * p99:
                    continue
                if excluded is None:
                    excluded = self._quarantined_names()
                tgt = self._least_loaded_other_locked(rep, excluded)
                if tgt is None:
                    continue
                grp = _HedgeGroup()
                r.hedge_group = grp
                clone = _Request(
                    routine=r.routine, key=r.key, A=r.A, B=r.B,
                    m=r.m, n=r.n, nrhs=r.nrhs, future=r.future,
                    deadline=r.deadline, retries=0, tenant=r.tenant,
                    priority=r.priority, tenanted=r.tenanted,
                    factor_fp=r.factor_fp, factor_miss=r.factor_miss,
                    is_hedge=True, hedge_group=grp,
                )
                # attempt=1 skips the queued-histogram observation and
                # the twin keeps the primary's clock (hedge latency is
                # the request's latency, would it ever be observed)
                clone.attempt = 1
                clone.t_submit = r.t_submit
                metrics.inc("serve.hedge.sent")
                if spans.is_on() and r.trace is not None:
                    spans.event(
                        "hedge", trace=r.trace, lane=tgt.lane,
                        reason="straggler", age_s=round(age, 4),
                    )
                sync.guarded(tgt, "q")
                tgt.q.appendleft(clone)
                hedged = True
        if hedged:
            # wake the target lanes — but ONLY when something was
            # enqueued: an unconditional notify from every worker's
            # pop loop would ping-pong idle workers out of their
            # cond.wait forever (a busy-spin on an idle service)
            self._cond.notify_all()


def _cert_operand(req: _Request) -> np.ndarray:
    """The operand a certificate must check AGAINST: gesv reads all of
    A, but posv references only the LOWER triangle (the api contract —
    "solves with the LOWER triangle of A"), so certifying against junk
    above the diagonal would fail every verdict on a numerically
    correct X and abandon a documented-valid request.  Mirrors the
    symmetrization the traced ``posv_check`` already does."""
    if req.routine != "posv":
        return req.A
    A = np.asarray(req.A)
    return np.tril(A) + np.conj(np.tril(A, -1)).T


# -- delivery taps (the soak recorder's hook) -------------------------------
#
# Module-level observers of request resolution: each tap is called
# ``tap(req, outcome)`` exactly where the request's future is about to
# resolve (outcome "ok" or the exception class name).  Zero overhead
# unarmed — the hot path pays ONE truthiness check on an empty list —
# and a tap can never break delivery (exceptions are swallowed).  A
# hedged pair fires once per member resolution; consumers that want
# one event per client request dedup on ``id(req.future)`` (twins
# share the future).  soak/record.py is the only in-tree consumer.

_delivery_taps: List[Callable[["_Request", str], None]] = []


def add_delivery_tap(fn: Callable[["_Request", str], None]) -> None:
    """Register a delivery observer (idempotent per function)."""
    if fn not in _delivery_taps:
        _delivery_taps.append(fn)


def remove_delivery_tap(fn: Callable[["_Request", str], None]) -> None:
    """Unregister a delivery observer (missing fn is a no-op)."""
    try:
        _delivery_taps.remove(fn)
    except ValueError:
        pass


def _fire_delivery_taps(req: "_Request", outcome: str) -> None:
    for tap in list(_delivery_taps):
        try:
            tap(req, outcome)
        except Exception:
            pass  # observability must never break delivery


def _finish_spans(req: Optional[_Request], outcome: str) -> None:
    """Close a request's span chain at resolution: any still-open
    queued span, then the root (idempotent — the first outcome wins,
    mirroring Future.set_result)."""
    if req is None or req.span is None or not spans.is_on():
        return
    spans.end(req.qspan, outcome=outcome)
    spans.end(req.span, outcome=outcome)


def _resolve(fut: Future, value, req: Optional[_Request] = None) -> None:
    _finish_spans(req, "ok")
    if _delivery_taps and req is not None:
        _fire_delivery_taps(req, "ok")
    # race plane: the worker's writes to the result happen-before any
    # thread that reads it off the future (one bool when off)
    sync.hb_publish(fut)
    g = req.hedge_group if req is not None else None
    if g is not None:
        # first correct result wins the shared future; the loser's
        # completed work is the hedge's cost, counted wasted
        if g.first_result():
            if not fut.done():
                fut.set_result(value)
            if req.is_hedge:
                metrics.inc("serve.hedge.won")
        else:
            metrics.inc("serve.hedge.wasted")
        return
    if not fut.done():
        fut.set_result(value)


def _resolve_exc(
    fut: Future, exc: Exception, req: Optional[_Request] = None
) -> None:
    _finish_spans(req, type(exc).__name__)
    if _delivery_taps and req is not None:
        _fire_delivery_taps(req, type(exc).__name__)
    sync.hb_publish(fut)  # hand-off edge, as in _resolve
    if req is not None and isinstance(exc, SlateError):
        exc.with_context(
            routine=req.routine,
            bucket=req.key.label if req.key is not None else None,
            attempt=req.attempt,
            # tenant identity only where tenancy is real (a request
            # admitted through the plane): default-path error strings
            # stay exactly as before
            tenant=req.tenant if req.tenanted else None,
            priority=(
                _bk.priority_name(req.priority) if req.tenanted else None
            ),
        )
    g = req.hedge_group if req is not None else None
    if g is not None:
        # a hedged pair fails only as a whole: one member's error is
        # suppressed while its twin can still deliver
        if g.member_failed() and not fut.done():
            fut.set_exception(exc)
        return
    if not fut.done():
        fut.set_exception(exc)
