"""Durable executable artifacts: the on-disk store that turns a warmed
bucket lattice from a *recipe* (the warmup manifest — a list of shapes
to recompile, minutes of compiles per process) into an *artifact* a
fresh serving replica loads instead of recompiling.

Layout (``SLATE_TPU_ARTIFACTS=/dir`` or ``ArtifactStore(root)``)::

    /dir/
      <routine>.<MxNxR>.<dtype>[...].b<batch>.<content12>.slate_exe
      xla-cache/          # persistent XLA compilation cache (seeded)
      .lock               # cross-process write lock

Each ``.slate_exe`` file is one JSON header line + ``\\n`` + payload
bytes.  The header carries the full **fingerprint**: the content half
(every BucketKey field including the PR3 ``schedule`` and PR5
``precision``, plus the batch point — ``buckets.content_fields``) and
the runtime half (jax/jaxlib version, backend, device kind, x64 mode —
:func:`runtime_fields`), plus a sha256 checksum of the payload and the
``mode`` the entry took:

* ``"export"`` — the payload is ``jax.export`` serialized StableHLO of
  the jitted bucket executable; load deserializes and re-jits it,
  skipping Python retracing and jax lowering entirely (and, with the
  seeded XLA cache below, the backend compile too).
* ``"cache_seed"`` — ``jax.export`` refused the computation (donated
  or sharded executables are version-dependent), the exported
  module embeds non-portable custom calls (vendor LAPACK on CPU,
  pallas — loading those in a fresh process can segfault, which no
  integrity check can catch), or the bucket is **mesh-sharded**
  (``BucketKey.mesh`` — shard_map programs are never trusted across
  processes; the entry is still keyed by its mesh shape, so it cannot
  collide with the single-device fingerprint); the payload is empty
  and the entry records that the build itself seeded the persistent
  XLA compilation cache under ``<root>/xla-cache``, so a fresh
  replica's recompile is a disk hit instead of a cold backend compile.

Robustness is the design center, because a persisted artifact is a new
thing that can be stale, truncated, or corrupt:

* **Atomic write-then-rename under a cross-process lock** — a reader
  (another replica restoring from the same dir) can never observe a
  torn artifact; the lock serializes writers and is stale-broken by
  age so a crashed writer cannot wedge the fleet.
* **Load-time integrity verification** — magic/header parse, full
  fingerprint match, and payload checksum.  *Any* mismatch degrades to
  a counted recompile and never crashes or serves wrong results:
  corrupt bytes -> ``serve.artifact_corrupt``, a fingerprint from a
  different jaxlib/device/x64/schedule -> ``serve.artifact_stale``,
  a deserialization error on verified bytes ->
  ``serve.artifact_load_fail``; hits and misses count
  ``serve.artifact_hit`` / ``serve.artifact_miss`` (each also emitted
  per bucket as ``serve.artifact.<label>.b<batch>.<outcome>`` for
  ``tools/artifact_report.py``).  A recompiled bucket re-saves,
  overwriting the bad file — the store self-heals.
* **Chaos coverage** — the ``artifact_corrupt`` / ``artifact_stale`` /
  ``artifact_load_fail`` fault sites (aux/faults) are threaded through
  :meth:`ArtifactStore.load`, so ``run_tests.py --coldstart`` can
  inject every failure mode and assert the recovery counters.

The degradation ladder, end to end: artifact hit (zero retrace, zero
compile) -> manifest recompile (warm the shape from the recipe, XLA
cache assisted) -> cold compile (nothing persisted).  Every rung
serves correct results; only the metrics differ.
"""

from __future__ import annotations

import json
import hashlib
import os
import re
import threading
import time
from typing import Callable, Optional, Tuple

from ..aux import faults, metrics, sync
from .buckets import BucketKey, content_fields, fingerprint

ARTIFACTS_ENV = "SLATE_TPU_ARTIFACTS"

MAGIC = "slate-artifact"
SCHEMA = 1
SUFFIX = ".slate_exe"

#: modes an artifact entry can record (header ``mode`` field)
MODE_EXPORT = "export"
MODE_CACHE_SEED = "cache_seed"

#: a .lock older than this is considered abandoned by a crashed writer
#: and broken (seconds); writers touch the lock only for the duration
#: of one tmp-write + rename, far below this
LOCK_STALE_S = 30.0
LOCK_RETRY_S = 0.02
LOCK_TIMEOUT_S = 10.0


#: custom-call targets that are portable across processes (partitioning
#: annotations resolved by the compiler, not function pointers).  Any
#: OTHER custom_call in an exported module — vendor LAPACK kernels on
#: CPU (``lapack_*_ffi``), pallas ``tpu_custom_call``s — is treated as
#: non-exportable and the entry falls back to the cache_seed rung:
#: jax.export nominally guarantees some of these stable, but a
#: deserialized ``lapack_dgetrf_ffi`` segfaults at execution in a
#: fresh process on this jaxlib, and a crash-safe store must not trust
#: a guarantee it can observe being broken.
_PORTABLE_CUSTOM_CALLS = frozenset({
    "Sharding",
    "SPMDFullToShardShape",
    "SPMDShardToFullShape",
    "annotate_device_placement",
})

_CUSTOM_CALL_RE = re.compile(
    r"stablehlo\.custom_call[^\n]*?@([\w.\-]+)"
    r"|call_target_name\s*=\s*\"([^\"]+)\""
)


def nonportable_custom_calls(exported) -> list:
    """Custom-call targets in an exported module that are not on the
    portable allowlist (empty = safe to serialize)."""
    try:
        txt = exported.mlir_module()
    except Exception:  # noqa: BLE001 — unreadable module: do not export it
        return ["<unreadable-module>"]
    targets = {t for pair in _CUSTOM_CALL_RE.findall(txt) for t in pair if t}
    return sorted(t for t in targets if t not in _PORTABLE_CUSTOM_CALLS)


def runtime_fields() -> dict:
    """The runtime half of the artifact fingerprint: serialized
    executables are only valid for the jax/jaxlib pair, backend,
    device kind, and x64 mode they were exported under — any drift
    must read as *stale*, never load."""
    import jax

    try:
        import jaxlib

        jaxlib_ver = getattr(jaxlib, "__version__", "?")
    except Exception:  # noqa: BLE001 — fingerprint must always build
        jaxlib_ver = "?"
    try:
        devs = jax.devices()
        device_kind = devs[0].device_kind if devs else "?"
    except Exception:  # noqa: BLE001
        device_kind = "?"
    return {
        "jax": getattr(jax, "__version__", "?"),
        "jaxlib": jaxlib_ver,
        "backend": jax.default_backend(),
        "device_kind": device_kind,
        "x64": bool(jax.config.jax_enable_x64),
    }


class _FileLock:
    """Cross-process advisory lock via O_CREAT|O_EXCL, with stale-break:
    a lock file older than LOCK_STALE_S belongs to a crashed writer and
    is removed (the subsequent create race is harmless — both writers
    produce whole files via rename; the lock only bounds concurrent
    write amplification, atomicity never depends on it)."""

    def __init__(self, path: str, timeout_s: float = LOCK_TIMEOUT_S,
                 stale_s: float = LOCK_STALE_S):
        self.path = path
        self.timeout_s = timeout_s
        self.stale_s = stale_s
        self._held = False

    def __enter__(self) -> "_FileLock":
        deadline = time.monotonic() + self.timeout_s
        while True:
            try:
                fd = os.open(self.path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                try:
                    os.write(fd, f"{os.getpid()}\n".encode())
                finally:
                    os.close(fd)
                self._held = True
                return self
            except FileExistsError:
                try:
                    age = time.time() - os.path.getmtime(self.path)
                    if age > self.stale_s:
                        os.unlink(self.path)  # crashed writer; break it
                        continue
                except OSError:
                    continue  # holder released between stat and unlink
                if time.monotonic() > deadline:
                    # proceed WITHOUT the lock rather than wedge the
                    # replica: rename keeps every write atomic anyway
                    metrics.inc("serve.artifact_lock_timeout")
                    return self
                time.sleep(LOCK_RETRY_S)

    def __exit__(self, *exc) -> bool:
        if self._held:
            try:
                os.unlink(self.path)
            except OSError:
                pass
            self._held = False
        return False


class ArtifactStore:
    """On-disk store of serialized bucket executables, keyed by content
    fingerprint.  Thread-safe; every public method degrades to "no
    artifact" on any filesystem or serialization trouble — the store
    must never take serving down with it."""

    def __init__(self, root: str, seed_xla_cache: bool = True):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)
        # sync.Lock: plain threading.Lock unless the race plane is on
        self._lock = sync.Lock(name="artifacts.ArtifactStore._lock")
        self._runtime: Optional[dict] = None  # resolved on first use
        # (key, batch) pairs whose load() verified a cache_seed entry
        # this process: the recompile that follows must not pay a
        # redundant export + byte-identical rewrite (see save callers)
        self._cache_seed_verified: set = set()
        if seed_xla_cache:
            self._seed_xla_cache()

    # -- identity ----------------------------------------------------------

    def _runtime_fields(self) -> dict:
        with self._lock:
            if self._runtime is None:
                self._runtime = runtime_fields()
            return dict(self._runtime)

    def fingerprint(self, key: BucketKey, batch: int) -> Tuple[str, dict]:
        """(hex digest, field dict) of one entry's full identity."""
        fields = {**content_fields(key, batch), **self._runtime_fields()}
        return fingerprint(fields), fields

    def path_for(self, key: BucketKey, batch: int) -> str:
        """The entry's filename: the human-readable bucket label plus a
        short *content*-only hash.  The runtime half of the fingerprint
        lives in the header, NOT the name — so an artifact written by a
        different jaxlib/device is *found* and diagnosed as stale
        (counted, recompiled) instead of silently missing."""
        chash = fingerprint(content_fields(key, batch))[:12]
        return os.path.join(
            self.root, f"{key.label}.b{int(batch)}.{chash}{SUFFIX}"
        )

    def _seed_xla_cache(self) -> None:
        """Point jax's persistent compilation cache into the store (the
        cache_seed fallback rung, and a backend-compile accelerator for
        the export rung's re-jit).  Never stomps an operator-configured
        cache dir; never raises.

        jax has ONE cache-dir knob per process, so only the first
        store created in a process can claim it: a later store with a
        different root counts ``serve.artifact_cache_unseeded`` — its
        cache_seed entries exist but are not backed by its own
        ``<root>/xla-cache`` (production replicas run one store; this
        mostly bites multi-store tests)."""
        try:
            import jax

            mine = os.path.join(self.root, "xla-cache")
            cur = jax.config.jax_compilation_cache_dir
            if cur:
                if os.path.abspath(cur) != mine:
                    # operator-configured, or another store claimed
                    # the single process-wide knob first
                    metrics.inc("serve.artifact_cache_unseeded")
                return
            jax.config.update("jax_compilation_cache_dir", mine)
            # cache every entry: serve executables are small programs
            # whose compiles are still seconds each on accelerators
            for knob, val in (
                ("jax_persistent_cache_min_compile_time_secs", 0.0),
                ("jax_persistent_cache_min_entry_size_bytes", -1),
            ):
                try:
                    jax.config.update(knob, val)
                except Exception:  # noqa: BLE001 — knob names drift
                    pass
        except Exception:  # noqa: BLE001 — seeding is best-effort
            pass

    # -- save --------------------------------------------------------------

    def save(self, key: BucketKey, batch: int, jitted, arg_specs) -> str:
        """Persist one built executable.  Tries ``jax.export`` first;
        when export refuses (donated/sharded computations are not
        serializable across versions) or the exported module embeds
        non-portable custom calls (vendor LAPACK on CPU, pallas — see
        :func:`nonportable_custom_calls`), the entry is recorded as
        ``cache_seed`` — the build that just happened has already
        seeded the persistent XLA cache.  Returns the mode written
        (``"export"`` | ``"cache_seed"``); never raises."""
        try:
            fp, fields = self.fingerprint(key, batch)
            mode = MODE_EXPORT
            payload = b""
            nonportable: list = []
            if getattr(key, "mesh", ""):
                # mesh-sharded executables always take the cache_seed
                # rung: a serialized shard_map program binds a device
                # assignment this jaxlib gives no cross-process
                # stability guarantee for (the same trust boundary as
                # the vendor-LAPACK segfault below).  The entry is
                # still KEYED by its mesh shape (content_fields carries
                # BucketKey.mesh), so it never collides with the
                # single-device fingerprint and its build still seeds
                # the persistent XLA cache for the next replica.
                mode = MODE_CACHE_SEED
                nonportable = [f"sharded-mesh:{key.mesh}"]
            else:
                try:
                    from jax import export as _export

                    exported = _export.export(jitted)(*arg_specs)
                    nonportable = nonportable_custom_calls(exported)
                    if nonportable:
                        # vendor LAPACK / pallas custom calls deserialize
                        # but can segfault at execution in a fresh process
                        # (observed: lapack_dgetrf_ffi on this jaxlib) —
                        # a crash-safe store must not persist them
                        mode = MODE_CACHE_SEED
                    else:
                        payload = exported.serialize()
                except Exception:  # noqa: BLE001 — unsupported computation
                    mode = MODE_CACHE_SEED
                    payload = b""
            header = {
                "magic": MAGIC,
                "schema": SCHEMA,
                "mode": mode,
                "fingerprint": fp,
                "fields": fields,
                "sha256": hashlib.sha256(payload).hexdigest(),
                "payload_bytes": len(payload),
                "created_unix": time.time(),
            }
            if nonportable:
                # why this entry took the cache_seed rung — surfaced
                # by entries()/tools so operators can see which
                # buckets will always recompile on this backend
                header["nonportable"] = nonportable
            blob = (json.dumps(header, sort_keys=True) + "\n").encode() + payload
            path = self.path_for(key, batch)
            tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
            with _FileLock(os.path.join(self.root, ".lock")):
                try:
                    with open(tmp, "wb") as f:
                        f.write(blob)
                        f.flush()
                        os.fsync(f.fileno())
                    os.replace(tmp, path)  # readers see whole files only
                finally:
                    try:
                        os.unlink(tmp)
                    except OSError:
                        pass
            metrics.inc("serve.artifact_saved")
            if metrics.is_on():
                metrics.inc(f"serve.artifact_saved_{mode}")
            return mode
        except Exception:  # noqa: BLE001 — persistence must never crash serving
            metrics.inc("serve.artifact_save_error")
            return MODE_CACHE_SEED

    # -- load --------------------------------------------------------------

    def _count(self, key: BucketKey, batch: int, outcome: str) -> None:
        if outcome != "cache_seed":
            # any other outcome invalidates a prior cache_seed verdict
            # (e.g. the entry rotted since): the next build must
            # re-save so the store self-heals
            with self._lock:
                self._cache_seed_verified.discard((key, int(batch)))
        if metrics.is_on():
            metrics.inc(f"serve.artifact_{outcome}")
            metrics.inc(f"serve.artifact.{key.label}.b{int(batch)}.{outcome}")

    def load(self, key: BucketKey, batch: int) -> Optional[Callable]:
        """Load one entry; returns the deserialized callable (ready for
        ``jax.jit``) or None when the caller must compile instead.

        The verification ladder — each rung counted, none fatal:
        missing file -> ``miss``; unparsable header or checksum
        mismatch -> ``corrupt``; fingerprint drift (jaxlib, device
        kind, x64, schedule, precision, ...) -> ``stale``;
        deserialization failure of verified bytes -> ``load_fail``;
        a ``cache_seed`` entry -> ``cache_seed`` (recompile, warmed by
        the persistent XLA cache).  Fault sites ``artifact_corrupt`` /
        ``artifact_stale`` / ``artifact_load_fail`` inject each rung."""
        path = self.path_for(key, batch)
        try:
            with open(path, "rb") as f:
                blob = f.read()
        except OSError:
            self._count(key, batch, "miss")
            return None
        try:
            if faults.fire("artifact_corrupt") is not None:
                blob = self._flip_byte(blob)
            nl = blob.find(b"\n")
            if nl < 0:
                raise ValueError("no header line")
            header = json.loads(blob[:nl].decode())
            payload = blob[nl + 1:]
            if header.get("magic") != MAGIC or header.get("schema") != SCHEMA:
                raise ValueError("bad magic/schema")
            if hashlib.sha256(payload).hexdigest() != header.get("sha256"):
                raise ValueError("payload checksum mismatch")
            if len(payload) != int(header.get("payload_bytes", -1)):
                raise ValueError("payload truncated")
        except (ValueError, KeyError, TypeError, UnicodeDecodeError):
            # torn/truncated/bit-rotted bytes: counted, recompiled;
            # the rebuild's save() overwrites the bad file (self-heal)
            self._count(key, batch, "corrupt")
            return None
        fp, _fields = self.fingerprint(key, batch)
        if faults.fire("artifact_stale") is not None:
            fp += "!stale"  # as if this process ran a different jaxlib
        if header.get("fingerprint") != fp:
            self._count(key, batch, "stale")
            return None
        if header.get("mode") == MODE_CACHE_SEED:
            # nothing to deserialize — the recompile this triggers is
            # served from the persistent XLA cache seeded at save time
            with self._lock:
                self._cache_seed_verified.add((key, int(batch)))
            self._count(key, batch, "cache_seed")
            return None
        try:
            faults.check("artifact_load_fail")
            from jax import export as _export

            exported = _export.deserialize(payload)
            self._count(key, batch, "hit")
            return exported.call
        except Exception:  # noqa: BLE001 — verified bytes can still fail to load
            self._count(key, batch, "load_fail")
            return None

    def verified_cache_seed(self, key: BucketKey, batch: int) -> bool:
        """True when a load() this process verified a current-
        fingerprint ``cache_seed`` entry for (key, batch) — the caller
        about to compile can skip a byte-identical re-save."""
        with self._lock:
            return (key, int(batch)) in self._cache_seed_verified

    @staticmethod
    def _flip_byte(blob: bytes) -> bytes:
        """One flipped payload byte (the artifact_corrupt injection —
        past the header so the checksum, not the JSON parse, catches
        it; integrity is the contract under test)."""
        if not blob:
            return blob
        nl = blob.find(b"\n")
        i = min(nl + 1, len(blob) - 1) if nl >= 0 else len(blob) - 1
        out = bytearray(blob)
        out[i] ^= 0x01
        return bytes(out)

    # -- introspection -----------------------------------------------------

    def entries(self) -> list:
        """Header dicts of every artifact in the store (corrupt headers
        reported with ``{"path": ..., "error": ...}``), for tools."""
        out = []
        try:
            names = sorted(os.listdir(self.root))
        except OSError:
            return out
        for name in names:
            if not name.endswith(SUFFIX):
                continue
            path = os.path.join(self.root, name)
            try:
                with open(path, "rb") as f:
                    head = f.readline()
                h = json.loads(head.decode())
                h["path"] = path
                out.append(h)
            except (OSError, ValueError, UnicodeDecodeError) as e:
                out.append({"path": path, "error": str(e)})
        return out


def store_from_env(
    artifact_dir: Optional[str] = None,
) -> Optional[ArtifactStore]:
    """Build the store from an explicit dir or ``SLATE_TPU_ARTIFACTS``;
    None when neither names a directory.  A store that cannot be
    created (read-only fs, ...) degrades to None — serving without
    durability beats not serving."""
    root = (
        artifact_dir if artifact_dir is not None
        else os.environ.get(ARTIFACTS_ENV) or None
    )
    if not root:
        return None
    try:
        return ArtifactStore(root)
    except OSError:
        metrics.inc("serve.artifact_store_error")
        return None
