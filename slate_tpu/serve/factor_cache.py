"""Serve-level factorization cache: factor once, solve many.

Real solver traffic re-uses A — one design matrix against a stream of
right-hand sides, one preconditioner across thousands of solves — yet
every ``serve.gesv/posv`` request pays the full O(n^3) factorization
even when A is byte-identical to the last request.  This module is the
Clipper-style caching layer (NSDI'17, PAPERS.md) extended from
*predictions* to *factors*: an LRU of factorizations keyed by a matrix
fingerprint, so a repeated-A solve costs O(n^2) — exactly the
``getrs``/``potrs`` split (permute + trsm) SLATE makes at the driver
layer, lifted to the serving tier.

Keying
------
:func:`matrix_fingerprint` — sha256 over A's bytes + dtype + shape +
routine family + factorization schedule + precision.  Any drift in any
component is a different factor identity: an entry can never be served
against an A it was not computed from (and the service's residual
validation backstops even that — see ``factor_stale`` below).

Entries
-------
A :class:`FactorEntry` holds the factor **padded to its serve bucket**
(``[[L, 0], [0, I]]`` / ``[[LU, 0], [0, I]]`` — the exact first operand
of the trsm-only ``phase="solve"`` bucket executable, see
serve/buckets.py), the true dimension, the net row permutation for LU,
and the replica lane that produced it (the service routes hits back to
that lane so the solve dispatch lands on the device already holding
the factor's compiled variant).

Budgets & lifecycle
-------------------
LRU with BOTH an entry-count and a byte budget
(``Option.ServeFactorCacheEntries`` / ``Option.ServeFactorCacheBytes``,
or the ``SLATE_TPU_FACTOR_CACHE`` env grammar below).  Explicit
invalidation (:meth:`FactorCache.invalidate` / ``invalidate_all`` —
``serve.invalidate(fp)`` at the api) and rank-k up/downdate for
incrementally-edited A (:meth:`FactorCache.update`): posv entries
update the cached Cholesky factor in O(k n^2) via
``ops/chol_kernels.chol_update``; LU has no comparably stable in-place
analogue, so gesv entries fall back to a counted refactor
(``serve.factor_cache.update_refactor``).  Eviction and invalidation
both degrade a later hit to a counted refactor — never a wrong X.

Activation
----------
Off by default (``Option.ServeFactorCache = False``): a service
without a cache has ``factor_cache is None`` and the hot path pays one
branch.  Enable per process with ``SLATE_TPU_FACTOR_CACHE=1`` (or
``entries=64,bytes=2e9``), per service with
``SolverService(factor_cache=FactorCache(...))``.

Metrics: ``serve.factor_cache.{hit,miss,evict,invalidate,update,
update_refactor,refactor,spill,stale}`` counters plus the
``serve.factor_cache.bytes`` / ``.entries`` gauges — each event also
emitted per bucket (``serve.factor_cache.<label>.<event>``) and per
fingerprint (``serve.factor_cache.fp.<fp12>.<event>``, the
``tools/factor_report.py`` join key).
"""

from __future__ import annotations

import hashlib
import os
from collections import OrderedDict
from dataclasses import dataclass
from typing import Dict, Optional

import numpy as np

from ..aux import metrics, sync
from .buckets import BucketKey

FACTOR_CACHE_ENV = "SLATE_TPU_FACTOR_CACHE"

DEFAULT_MAX_ENTRIES = 32
DEFAULT_MAX_BYTES = 1 << 30  # 1 GiB of factors


# ---------------------------------------------------------------------------
# fingerprinting
# ---------------------------------------------------------------------------


def matrix_fingerprint(
    A: np.ndarray,
    routine: str,
    schedule: str = "auto",
    precision: str = "full",
) -> str:
    """sha256 hex digest of one matrix's factor identity: A's bytes +
    dtype + shape + routine family + schedule + precision.  The
    schedule/precision components are part of the identity because the
    factor the cache stores was produced under them — a deployment
    that flips Option.Schedule must refactor, not reuse."""
    A = np.ascontiguousarray(A)
    h = hashlib.sha256()
    h.update(
        f"{routine}|{np.dtype(A.dtype).name}|{A.shape[0]}x{A.shape[1]}"
        f"|{schedule}|{precision}|".encode()
    )
    h.update(A.data)
    return h.hexdigest()


#: cardinality cap on the per-fingerprint metric family: unlike every
#: other serve.* family (bounded by bucket labels), fp-keyed counters
#: grow with DISTINCT matrices — a churning-A service would otherwise
#: leak one registry key per request, forever.  Past the cap, events
#: still count globally and per bucket; the overflow itself is counted.
#: (``metrics.CappedKeys`` — the same guard the admission plane puts on
#: its ``serve.tenant.<id>.*`` families.)
FP_METRIC_CAP = 256
_fp_keys = metrics.CappedKeys(FP_METRIC_CAP)


def record(event: str, fp: Optional[str] = None,
           label: Optional[str] = None, n: int = 1) -> None:
    """One factor-cache event into the metrics registry: global +
    per-bucket + per-fingerprint (12-hex prefix — the factor_report
    join key, capped at :data:`FP_METRIC_CAP` distinct fingerprints),
    mirroring the serve.artifact_* naming scheme."""
    if not metrics.is_on():
        return  # hit-path caller: no f-string names built while off
    metrics.inc(f"serve.factor_cache.{event}", n)
    if label:
        metrics.inc(f"serve.factor_cache.{label}.{event}", n)
    if fp:
        fp12 = fp[:12]
        if _fp_keys.track(fp12):
            metrics.inc(f"serve.factor_cache.fp.{fp12}.{event}", n)
        else:
            metrics.inc("serve.factor_cache.fp_overflow", n)


def _fp_gauge(fp: str, value: float) -> None:
    """Per-fingerprint bytes gauge, under the same cardinality cap."""
    if not metrics.is_on():
        return
    fp12 = fp[:12]
    if _fp_keys.track(fp12):
        metrics.gauge(f"serve.factor_cache.fp.{fp12}.bytes", value)


# ---------------------------------------------------------------------------
# entries
# ---------------------------------------------------------------------------


@dataclass(eq=False)
class FactorEntry:
    """One cached factorization, ready for the solve-phase executable.

    ``eq=False``: entries are identities, not values — the generated
    ``__eq__`` would compare the ndarray factor (truthiness raises),
    the same hazard PR12 fixed on ``service._Request``."""

    fp: str  # matrix_fingerprint of the A it was computed from
    routine: str  # gesv | posv | gels
    key: BucketKey  # the FULL-phase bucket key of the request stream
    # bucket-padded factor global: (S, S) LU or L for gesv/posv, the
    # (Mb + kt*nb, Nb) packed V/R + compact-WY T pack for gels
    # (buckets.solve_factor_shape) — always the EXACT first operand of
    # the solve-phase bucket executable
    factor: np.ndarray
    perm: Optional[np.ndarray]  # (n,) forward row permutation (gesv)
    n: int  # true solution dimension (rows of X: n of A, gels columns)
    replica: Optional[str] = None  # lane that factored it (device affinity)

    @property
    def nbytes(self) -> int:
        return int(self.factor.nbytes) + (
            int(self.perm.nbytes) if self.perm is not None else 0
        )

    @property
    def solve_key(self) -> BucketKey:
        return self.key.solve_sibling()


# ---------------------------------------------------------------------------
# factor production / direct solve-from-factor (driver entry points)
# ---------------------------------------------------------------------------


def factor_only(routine: str, A: np.ndarray, schedule: str = "auto"):
    """Factor one TRUE-shape A through the drivers; returns
    ``(factor_global, perm_or_None)``.  gesv: getrf (LU + net forward
    row permutation, truncated to the leading n rows — the drivers'
    identity-spliced padding guarantees partial pivoting never pulls a
    pad row into the leading block); posv: potrf (clean lower L).
    Raises NumericalError on a nonzero info — a failed factor is never
    cached."""
    from ..drivers import chol as _chol
    from ..drivers import lu as _lu
    from ..enums import Option, Uplo
    from ..exceptions import NumericalError
    from ..matrix.matrix import HermitianMatrix, Matrix

    n = A.shape[0]
    nb = min(64, n)
    opts = {Option.Schedule: schedule}
    if routine == "gesv":
        LU, piv, info = _lu.getrf(Matrix.from_global(A, nb), opts)
        if int(info) != 0:
            raise NumericalError(
                f"getrf: singular U({int(info)})", int(info)
            ).with_context(routine=routine)
        perm = np.asarray(piv.perm)[:n].astype(np.int64)
        if perm.size and int(perm.max()) >= n:
            # cannot happen for the identity-spliced padded LU, but a
            # factor whose permutation escapes the leading block could
            # not be replayed against a bucket-padded B — refuse to
            # cache rather than risk a wrong X
            raise NumericalError(
                "getrf: pivot escaped the leading block"
            ).with_context(routine=routine)
        return np.asarray(LU.to_global()), perm
    if routine == "posv":
        L, info = _chol.potrf(
            HermitianMatrix.from_global(A, nb, uplo=Uplo.Lower), opts
        )
        if int(info) != 0:
            raise NumericalError(
                f"potrf: not SPD at {int(info)}", int(info)
            ).with_context(routine=routine)
        return np.tril(np.asarray(L.to_global())), None
    raise ValueError(f"factor cache supports gesv/posv, not {routine!r}")


def gels_factor_pack(
    A: np.ndarray, key: BucketKey, schedule: str = "auto"
) -> np.ndarray:
    """Factor one TRUE-shape tall A (m >= n) for the gels solve-phase
    bucket: pad to the bucket's (Mb, Nb) tall shape (zero rows + unit
    pad columns keep full column rank, so factoring the PADDED A
    directly is correct — see buckets.pad_tall), geqrf it once, and
    pack the V/R global together with every panel's compact-WY T
    factor into one ``buckets.solve_factor_shape(key)`` array.  The
    pack is the EXACT first operand of the gels solve executable
    (``drivers/qr.gels_solve_from_global``): each later same-A solve
    applies the cached block reflectors (no larft rebuild) plus one
    trsm — O(m n nrhs) instead of the O(m n^2) refactor."""
    from ..drivers import qr as _qr
    from ..enums import Option
    from ..matrix.matrix import Matrix
    from .buckets import gels_pack_kt, pad_tall, solve_factor_shape

    Ap = pad_tall(np.ascontiguousarray(A), key.m, key.n)
    fac, T = _qr.geqrf(
        Matrix.from_global(Ap, key.nb), {Option.Schedule: schedule}
    )
    VR = np.asarray(fac.to_global())
    Ts = np.asarray(T.T)
    pack = np.zeros(solve_factor_shape(key), dtype=VR.dtype)
    pack[: key.m] = VR
    for k in range(gels_pack_kt(key)):
        w = min(key.nb, key.n - k * key.nb)
        pack[
            key.m + k * key.nb : key.m + k * key.nb + w, :w
        ] = Ts[k][:w, :w]
    return pack


def solve_from_factor(entry: FactorEntry, B: np.ndarray) -> np.ndarray:
    """Direct (unbatched, eager) trsm-only solve from a cached entry —
    the same math as the solve-phase bucket executable, used when a
    same-A request finds the factor mid-flight (a burst whose first
    member just factored) and by parity checks."""
    from ..drivers import chol as _chol
    from ..drivers import lu as _lu
    from ..drivers import qr as _qr

    n = entry.n
    B = np.asarray(B)
    if entry.routine == "gels":
        # pack solve: pad B rows to the bucket height (pad rows carry
        # zeros, so the pad columns contribute nothing to the cropped X)
        Bp = np.zeros((entry.key.m, B.shape[1]), dtype=B.dtype)
        Bp[: B.shape[0]] = B
        X = _qr.gels_solve_from_global(
            entry.factor, Bp, entry.key.m, entry.key.nb
        )
        return np.asarray(X)[:n]
    F = entry.factor[:n, :n]
    if entry.routine == "gesv":
        X = _lu.getrs_from_global(F, B[entry.perm])
    else:
        X = _chol.potrs_from_global(F, B)
    return np.asarray(X)


def residual_ok(
    A: np.ndarray, B: np.ndarray, X: np.ndarray, routine: str = "gesv"
) -> bool:
    """Normwise backward-residual check of one served solve:
    ``max|A X - B| <= sqrt(eps) * (|A|_inf |X|_inf + |B|_inf)``.  A
    numerically stable solve sits at ~n*eps regardless of cond(A); a
    factor that no longer matches A (the ``factor_stale`` chaos site,
    bit rot, a mis-applied update) lands at O(1) — orders past the
    sqrt(eps) fence, so the hit path re-solves direct instead of
    delivering a wrong X.

    gels: the least-squares residual ``A X - B`` is legitimately
    nonzero at the minimizer, so the fence moves to the normal
    equations — ``max|A^H (A X - B)|`` vanishes at the true LS
    solution and lands at O(|A| scale) for a stale factor."""
    if not np.all(np.isfinite(X)):
        return False
    dt = np.result_type(A, X)
    eps = np.finfo(np.dtype(dt).type(0).real.dtype).eps
    anrm = np.abs(A).max(initial=0.0)
    xmax = np.abs(X).max(initial=0.0)
    bmax = np.abs(B).max(initial=0.0)
    if routine == "gels":
        R = A.conj().T @ (A @ X - B)
        scale = anrm * (anrm * xmax + bmax)
    else:
        R = A @ X - B
        scale = anrm * xmax + bmax
    return float(np.abs(R).max(initial=0.0)) <= np.sqrt(eps) * max(
        scale, eps
    )


# jitted rank-k Cholesky up/downdate, cached per (downdate, shape/dtype
# via jax's own cache); downdate is a static python bool
_update_jits: Dict[bool, object] = {}
_update_lock = sync.Lock(name="factor_cache._update_lock")


def _chol_update_jit(downdate: bool):
    import functools

    import jax

    from ..ops import chol_kernels

    with _update_lock:
        fn = _update_jits.get(bool(downdate))
        if fn is None:
            fn = jax.jit(functools.partial(
                chol_kernels.chol_update, downdate=bool(downdate)
            ))
            _update_jits[bool(downdate)] = fn
        return fn


# ---------------------------------------------------------------------------
# the cache
# ---------------------------------------------------------------------------


class FactorCache:
    """LRU factor cache with an entry-count and a byte budget.
    Thread-safe (admission and every replica worker touch it); all
    bookkeeping is O(1) per operation plus the eviction walk."""

    def __init__(
        self,
        max_entries: int = DEFAULT_MAX_ENTRIES,
        max_bytes: int = DEFAULT_MAX_BYTES,
    ):
        self.max_entries = max(int(max_entries), 1)
        self.max_bytes = max(int(max_bytes), 1)
        # sync.RLock: plain threading.RLock unless SLATE_TPU_SYNC_CHECK
        # armed the race plane.  Admission and every replica worker
        # race on the LRU — the annotations are ground truth for the
        # lock-discipline / race-guarded-by lint rules
        self._lock = sync.RLock(name="factor_cache.FactorCache._lock")
        self._entries: "OrderedDict[str, FactorEntry]" = OrderedDict()  # guarded by: _lock
        self._bytes = 0  # guarded by: _lock

    # -- introspection -----------------------------------------------------

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    @property
    def bytes(self) -> int:
        with self._lock:
            return self._bytes

    def fingerprints(self) -> list:
        """Cached fingerprints, least-recently-used first."""
        with self._lock:
            return list(self._entries)

    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "bytes": self._bytes,
                "max_entries": self.max_entries,
                "max_bytes": self.max_bytes,
            }

    def _gauges_locked(self) -> None:
        metrics.gauge("serve.factor_cache.bytes", self._bytes)
        metrics.gauge("serve.factor_cache.entries", len(self._entries))

    # -- core --------------------------------------------------------------

    def get(self, fp: str) -> Optional[FactorEntry]:
        """The entry for one fingerprint (refreshing its LRU position),
        or None.  Does NOT count hit/miss — the service counts those at
        the dispatch that actually serves (or misses) the factor."""
        with self._lock:
            sync.guarded(self, "_entries")  # race-plane probe (no-op off)
            entry = self._entries.get(fp)
            if entry is not None:
                self._entries.move_to_end(fp)
            return entry

    def put(self, entry: FactorEntry, replica: Optional[str] = None) -> bool:
        """Insert (or refresh) one entry, evicting LRU entries past
        either budget.  Returns False when the entry ALONE exceeds the
        byte budget (uncacheable — counted, never stored: a later
        repeat of that A refactors, which is the budget doing its
        job)."""
        if replica is not None:
            entry.replica = replica
        if entry.nbytes > self.max_bytes:
            record("uncacheable", fp=entry.fp, label=entry.key.label)
            return False
        with self._lock:
            sync.guarded(self, "_entries")  # race-plane probe (no-op off)
            old = self._entries.pop(entry.fp, None)
            if old is not None:
                self._bytes -= old.nbytes
            self._entries[entry.fp] = entry
            self._bytes += entry.nbytes
            while self._entries and (
                len(self._entries) > self.max_entries
                or self._bytes > self.max_bytes
            ):
                vfp, victim = self._entries.popitem(last=False)
                self._bytes -= victim.nbytes
                record("evict", fp=vfp, label=victim.key.label)
                _fp_gauge(vfp, 0)
            if entry.fp in self._entries:
                _fp_gauge(entry.fp, entry.nbytes)
            self._gauges_locked()
            return entry.fp in self._entries

    def invalidate(self, fp: str) -> bool:
        """Drop one fingerprint's factor; the next same-A request pays
        a counted refactor.  Returns whether it was present."""
        with self._lock:
            entry = self._entries.pop(fp, None)
            if entry is None:
                return False
            self._bytes -= entry.nbytes
            record("invalidate", fp=fp, label=entry.key.label)
            _fp_gauge(fp, 0)
            self._gauges_locked()
            return True

    def invalidate_all(self) -> int:
        """Drop every factor; returns the count dropped."""
        with self._lock:
            n = len(self._entries)
            for fp, entry in self._entries.items():
                record("invalidate", fp=fp, label=entry.key.label)
                _fp_gauge(fp, 0)
            self._entries.clear()
            self._bytes = 0
            self._gauges_locked()
            return n

    def rehome(self, old_replica: str,
               new_replica: Optional[str]) -> int:
        """Reassign every entry homed on ``old_replica`` to
        ``new_replica`` (scale-down: a removed lane's factors keep
        serving hits from a surviving lane instead of forcing counted
        refactors; LRU positions are untouched — re-homing is not a
        use).  ``new_replica=None`` un-pins them (any lane may serve
        the hit's solve dispatch on its own device).  Returns the
        count moved."""
        moved = 0
        with self._lock:
            sync.guarded(self, "_entries")  # race-plane probe (no-op off)
            for entry in self._entries.values():
                if entry.replica == old_replica:
                    entry.replica = new_replica
                    moved += 1
        if moved:
            record("rehome", n=moved)
        return moved

    # -- rank-k up/downdate ------------------------------------------------

    def update(
        self,
        fp: str,
        A_new: np.ndarray,
        U: np.ndarray,
        downdate: bool = False,
    ) -> Optional[str]:
        """Re-key one entry to an incrementally-edited A:
        ``A_new = A ± U U^H`` (update / downdate, U of shape (n, k) or
        (n,)).  posv entries apply the O(k n^2) Cholesky up/downdate
        kernel to the cached factor; gesv entries — and any posv
        up/downdate that breaks down (a downdate past positive
        definiteness) — fall back to a full refactor of ``A_new``
        (``serve.factor_cache.update_refactor``).  Either way the
        entry is re-keyed to ``matrix_fingerprint(A_new)``, so the
        caller's next ``submit(A_new, B)`` hits.  Returns the new
        fingerprint, or None when ``fp`` is not cached (the caller
        should just submit A_new and let the miss path factor it)."""
        with self._lock:
            entry = self._entries.get(fp)
            if entry is not None and entry.routine == "gels":
                # rank-k A +- U U^H edits are square-matrix semantics;
                # row-streamed least-squares updating lives in
                # fabric.session (Householder row appends on R)
                raise ValueError(
                    "update: gels factors are row-streamed via "
                    "serve.session(routine='gels'), not rank-k updated"
                )
            entry = self._entries.pop(fp, None)
            if entry is not None:
                self._bytes -= entry.nbytes
        if entry is None:
            return None
        A_new = np.ascontiguousarray(A_new)
        if A_new.shape[0] != entry.n:
            # a different-size A is a different problem, not an update
            self.put(entry)  # put the untouched entry back
            raise ValueError(
                f"update: A_new is {A_new.shape[0]}x{A_new.shape[1]}, "
                f"entry holds n={entry.n}"
            )
        new_fp = matrix_fingerprint(
            A_new, entry.routine, schedule=entry.key.schedule,
            precision=entry.key.precision,
        )
        factor = None
        perm = entry.perm
        if entry.routine == "posv":
            U2 = np.asarray(U, dtype=entry.factor.dtype)
            if U2.ndim == 1:
                U2 = U2[:, None]
            S = entry.factor.shape[0]
            Up = np.zeros((S, U2.shape[1]), dtype=entry.factor.dtype)
            Up[: entry.n] = U2  # pad rows untouched: I stays I
            F = np.asarray(_chol_update_jit(bool(downdate))(
                entry.factor, Up
            ))
            if np.all(np.isfinite(F)):
                factor = F
                record("update", fp=new_fp, label=entry.key.label)
            # non-finite = downdate breakdown (A_new not SPD under the
            # cached factor's rounding): refactor from A_new below
        if factor is None:
            from .buckets import pad_square

            raw, perm = factor_only(
                entry.routine, A_new, schedule=entry.key.schedule
            )
            factor = pad_square(raw, entry.factor.shape[0])
            record("update", fp=new_fp, label=entry.key.label)
            record("update_refactor", fp=new_fp, label=entry.key.label)
        new_entry = FactorEntry(
            fp=new_fp, routine=entry.routine, key=entry.key,
            factor=factor, perm=perm, n=entry.n, replica=entry.replica,
        )
        self.put(new_entry)
        return new_fp


# ---------------------------------------------------------------------------
# env/options activation: SLATE_TPU_FACTOR_CACHE=1 | entries=N,bytes=M
# ---------------------------------------------------------------------------


def parse_env_spec(spec: str) -> Optional[dict]:
    """Parse the ``SLATE_TPU_FACTOR_CACHE`` grammar: empty/``0``/``off``
    -> None (disabled), ``1``/``on`` -> enabled with defaults, or a
    comma list of ``entries=<int>`` / ``bytes=<float>`` overrides."""
    spec = (spec or "").strip()
    if not spec or spec.lower() in ("0", "off", "false", "no"):
        return None
    if spec.lower() in ("1", "on", "true", "yes"):
        return {}
    out: dict = {}
    for item in spec.split(","):
        item = item.strip()
        if not item:
            continue
        k, sep, v = item.partition("=")
        k, v = k.strip().lower(), v.strip()
        if not sep:
            raise ValueError(
                f"{FACTOR_CACHE_ENV}={spec!r}: expected k=v, got {item!r}"
            )
        if k == "entries":
            out["max_entries"] = int(v)
        elif k == "bytes":
            out["max_bytes"] = int(float(v))
        else:
            raise ValueError(
                f"{FACTOR_CACHE_ENV}={spec!r}: unknown key {k!r} "
                "(entries|bytes)"
            )
    return out


def cache_from_options(opts=None) -> Optional[FactorCache]:
    """Resolve the process/service default: ``SLATE_TPU_FACTOR_CACHE``
    wins (env grammar above), else ``Option.ServeFactorCache`` with the
    ``ServeFactorCacheEntries`` / ``ServeFactorCacheBytes`` budgets.
    None = disabled — the service hot path stays one branch."""
    from ..enums import Option
    from ..options import get_option

    kw = parse_env_spec(os.environ.get(FACTOR_CACHE_ENV, ""))
    if kw is None:
        if not bool(get_option(opts, Option.ServeFactorCache)):
            return None
        kw = {}
    kw.setdefault(
        "max_entries",
        int(get_option(opts, Option.ServeFactorCacheEntries)),
    )
    kw.setdefault(
        "max_bytes", int(get_option(opts, Option.ServeFactorCacheBytes))
    )
    return FactorCache(**kw)
