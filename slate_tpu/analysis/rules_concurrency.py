"""Concurrency and failure-semantics rules: lock discipline over
annotated shared fields, and exception-context hygiene on serve-path
raises.

Bug classes mechanized (CHANGES.md):

* PR4's inline-resolution flake and later review passes: shared mutable
  state of the threaded serve pool touched outside the owning lock.
  Fields annotated ``# guarded by: <lock>`` become machine-checked —
  every access in the file must sit inside ``with *.<lock>:``, in a
  function whose name ends with ``_locked`` (the repo's
  caller-holds-the-lock convention), or in ``__init__`` (construction
  precedes sharing).  The ``(external)`` variant documents state whose
  synchronization lives in a *caller's* lock (FairQueue under the
  service condition): accesses inside the declaring class are the
  documented contract and only outside access is checked.
* Serve-path raises of :class:`SlateError` subclasses without
  ``with_context()`` strip the routine/bucket/tenant triage fields the
  exception hierarchy exists to carry — every review pass has had to
  re-add them by hand.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, NamedTuple, Optional, Set

from .core import (
    FileInfo,
    Finding,
    Project,
    Rule,
    enclosing_function,
    parents,
    rule,
    terminal_name,
)

_GUARD_RE = re.compile(
    r"#\s*guarded by:\s*([A-Za-z_][A-Za-z0-9_]*)\s*(\(external\))?"
)


class _Guard(NamedTuple):
    attr: str
    lock: str
    external: bool
    klass: ast.ClassDef
    line: int


def iter_attr_decls(f: FileInfo):
    """Every class-attribute definition site in one file, as ``(attr,
    class node, lineno, guard match-or-None)``: ``self.x = ...`` in a
    method body, or a bare/annotated class-level field.  The ONE
    spelling of the declaration walk — the intraprocedural
    ``lock-discipline`` rule and the whole-program ``races`` rules
    both build on it, so they can never diverge on which fields they
    consider annotated."""
    for node in ast.walk(f.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        for sub in ast.walk(node):
            attr = None
            if isinstance(sub, (ast.Assign, ast.AnnAssign)):
                tgt = (
                    sub.targets[0] if isinstance(sub, ast.Assign)
                    else sub.target
                )
                if (
                    isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"
                ):
                    attr = tgt.attr
                elif (
                    isinstance(tgt, ast.Name)
                    and enclosing_function(sub) is None
                ):
                    # class-level field (dataclass style): a bare-Name
                    # assignment directly in the class body, NOT a
                    # local variable inside a method (which must never
                    # register a guard for that name file-wide)
                    attr = tgt.id
            if attr is None:
                continue
            yield attr, node, sub.lineno, _GUARD_RE.search(
                f.line_text(sub.lineno)
            )


def _guards(f: FileInfo) -> List[_Guard]:
    """``# guarded by:`` annotations on attribute definitions, per
    class: ``self.q = ...  # guarded by: _cond`` in a method body, or
    an annotated class-level field."""
    return [
        _Guard(attr, m.group(1), bool(m.group(2)), node, lineno)
        for attr, node, lineno, m in iter_attr_decls(f)
        if m
    ]


def _under_lock(node: ast.AST, lock: str) -> bool:
    for anc in parents(node):
        if isinstance(anc, ast.With):
            for item in anc.items:
                if terminal_name(item.context_expr) == lock:
                    return True
    return False


@rule
class LockDiscipline(Rule):
    """Accesses to ``# guarded by: <lock>``-annotated attributes must
    hold the lock (intraprocedural; ``_locked``-suffix functions and
    ``__init__`` are the documented exemptions)."""

    name = "lock-discipline"
    summary = (
        "attributes annotated '# guarded by: <lock>' are only touched "
        "under `with *.<lock>:` (or in *_locked/__init__ functions)"
    )
    bug = "lock-discipline races in the threaded serve pool"

    def check_file(self, f: FileInfo, project: Project):
        guards = _guards(f)
        if not guards:
            return
        # matching is by attribute NAME (intraprocedural — no type
        # inference), so one name may carry several guards from
        # different classes: an access is clean when it satisfies ANY
        # of them, and flagged only when it satisfies none
        by_attr: Dict[str, List[_Guard]] = {}
        for g in guards:
            by_attr.setdefault(g.attr, []).append(g)
        ann_lines = {g.line for g in guards}
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.Attribute):
                continue
            gs = by_attr.get(node.attr)
            if gs is None or node.lineno in ann_lines:
                continue
            encl = enclosing_function(node)
            fname = getattr(encl, "name", "")
            if fname == "__init__" or fname.endswith("_locked"):
                continue
            ok = False
            for g in gs:
                if g.external and any(
                    anc is g.klass for anc in parents(node)
                ):
                    ok = True  # the class's methods ARE the documented API
                    break
                if _under_lock(node, g.lock):
                    ok = True
                    break
            if ok:
                continue
            locks = "/".join(sorted({g.lock for g in gs}))
            lines = ", ".join(str(g.line) for g in gs)
            yield Finding(
                self.name, f.rel, node.lineno, node.col_offset,
                f"access to {node.attr!r} (guarded by {locks!r}, "
                f"declared at line {lines}) outside `with "
                f"*.{locks}:` — take the lock, move the access into a "
                "*_locked helper, or suppress with a justification if "
                "the race is deliberate",
            )


# ---------------------------------------------------------------------------
# exception taxonomy
# ---------------------------------------------------------------------------


def slate_error_names(project: Project) -> Set[str]:
    """Class names transitively inheriting SlateError across the linted
    tree (exceptions.py plus serve-local subclasses like Rejected)."""
    cached = project.cache.get("slate_errors")
    if cached is not None:
        return cached  # type: ignore[return-value]
    known: Set[str] = {"SlateError"}
    classes: List[ast.ClassDef] = [
        node
        for f in project.files
        for node in ast.walk(f.tree)
        if isinstance(node, ast.ClassDef)
    ]
    changed = True
    while changed:
        changed = False
        for node in classes:
            if node.name in known:
                continue
            if any((terminal_name(b) or "") in known for b in node.bases):
                known.add(node.name)
                changed = True
    project.cache["slate_errors"] = known
    return known


@rule
class ExceptionContext(Rule):
    """Serve-path ``raise SlateErrorSubclass(...)`` must chain
    ``.with_context(...)`` so the future's exception carries
    routine/bucket/tenant triage fields."""

    name = "exception-context"
    summary = (
        "serve-path raises of SlateError subclasses attach "
        ".with_context(...)"
    )
    bug = "context-less serve exceptions forcing log-scrape triage"

    scope_prefix = "slate_tpu/serve/"

    def check_file(self, f: FileInfo, project: Project):
        if not f.rel.startswith(self.scope_prefix):
            return
        errors = slate_error_names(project)
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.Raise) or node.exc is None:
                continue
            exc = node.exc
            if not isinstance(exc, ast.Call):
                continue  # bare re-raise / `raise e` keep their context
            if (
                isinstance(exc.func, ast.Attribute)
                and exc.func.attr == "with_context"
            ):
                continue
            cls = terminal_name(exc.func)
            if cls not in errors:
                continue
            encl = enclosing_function(node)
            if getattr(encl, "name", "") == "__init__" or encl is None:
                # construction-time config errors carry no request
                continue
            yield Finding(
                self.name, f.rel, node.lineno, node.col_offset,
                f"raise {cls}(...) without .with_context(...) — attach "
                "routine/bucket/tenant so operators triage from the "
                "exception object, not the logs",
            )
