"""Race & deadlock rules: whole-program guarded-by analysis and the
static lock-order graph (``aux/sync.py`` is the dynamic half of the
same plane).

Bug classes mechanized (CHANGES.md):

* PR14's review passes caught three real concurrency bugs — an
  idle-worker busy-spin from an unconditional notify, hedge clones
  landing on quarantined lanes, stop()-raced re-enqueues that would
  hang futures.  PR13's ``lock-discipline`` rule checks ``# guarded
  by:`` annotations only *intraprocedurally, file by file*: a
  ``*_locked`` helper is exempt (caller holds the lock) but nothing
  checked its CALLERS, and an annotated field read from another module
  was invisible.  ``race-guarded-by`` closes both holes: it follows
  call edges from every ``*_locked`` helper to its callers and extends
  field checking across modules wherever the attribute name resolves
  unambiguously — superseding the intraprocedural rule, which stays as
  the fallback for unresolvable names.
* A deadlock needs two locks taken in two orders — invisible to any
  single-file rule.  ``race-lock-order`` builds the global acquisition
  graph from every nested ``with <lock>:`` / ``.acquire()`` region
  across ``serve/``, ``integrity/`` and ``aux/`` — following calls
  made while a lock is held, so ``with self._cond:`` calling
  ``adm.quota_take`` (which takes the admission lock) is an edge even
  though no ``with`` nests lexically.  A cycle is a potential deadlock
  finding, and the shipped graph is emitted as a checked-in artifact
  (:data:`LOCK_GRAPH_NAME`) so every NEW edge shows up in review
  before it can close a cycle in production.

Resolution discipline (no type inference, so precision comes from
refusing to guess):

* A guarded attribute is **resolvable project-wide** iff every class
  in the linted tree that defines it carries a guard annotation for it
  (or only one class defines it).  ``state`` (Breaker unguarded,
  IntegrityScore guarded) is ambiguous → same-file checking only;
  ``level`` (OverloadController alone) is resolvable → a lock-free
  read from ``serve/service.py`` is flagged unless suppressed with a
  justification.
* A call is **followed** for lock-set propagation only when it
  resolves deterministically: ``self.m()`` to the enclosing class,
  bare names to the same module, ``alias.m()`` through the file's
  project imports, and other ``obj.m()`` only when ``m`` is defined
  exactly once in scope and is not a builtin-container-shaped name
  (``.get()``/``.append()``/... are never followed — by-name matching
  there would wire bogus edges through every dict lookup).
"""

from __future__ import annotations

import ast
import json
import os
from typing import Dict, FrozenSet, List, NamedTuple, Optional, Set, Tuple

from .core import (
    FileInfo,
    Finding,
    Project,
    Rule,
    enclosing_function,
    rule,
    terminal_name,
)
from .rules_concurrency import _under_lock, iter_attr_decls

#: the checked-in lock-order graph artifact (repo root) — regenerate
#: with ``tools/slate_lint.py --write-lock-graph`` after reviewing a
#: new edge
LOCK_GRAPH_NAME = "LOCK_ORDER.json"

#: directories whose nested lock regions feed the lock-order graph
LOCK_SCOPE = (
    "slate_tpu/serve/", "slate_tpu/integrity/", "slate_tpu/aux/",
    "slate_tpu/fleet/",
)

#: constructors that declare a lock (threading primitives and their
#: aux/sync drop-in wrappers)
_LOCK_CTORS = {"Lock", "RLock", "Condition"}
_LOCK_ROOTS = {"threading", "sync"}

#: attribute-call names never followed by the unique-name fallback:
#: container/str/thread/lock/future API lookalikes whose by-name
#: resolution would wire bogus edges through every dict lookup
_CALL_DENY = frozenset({
    "get", "pop", "popitem", "popleft", "append", "appendleft", "remove",
    "clear", "update", "items", "keys", "values", "add", "discard",
    "extend", "insert", "setdefault", "sort", "index", "count", "copy",
    "join", "split", "rsplit", "strip", "lstrip", "rstrip", "partition",
    "rpartition", "startswith", "endswith", "format", "encode", "decode",
    "lower", "upper", "replace", "search", "match", "findall", "finditer",
    "group", "wait", "wait_for", "notify", "notify_all", "acquire",
    "release", "locked", "set", "is_set", "is_alive", "start", "cancel",
    "result", "set_result", "set_exception", "done", "move_to_end",
})


# ---------------------------------------------------------------------------
# project-wide guard table
# ---------------------------------------------------------------------------


class _Decl(NamedTuple):
    """One class-attribute definition site (guarded or not)."""

    attr: str
    rel: str
    klass: str
    line: int
    lock: Optional[str]  # None = defined without a guard annotation
    external: bool


def _attr_decls(f: FileInfo) -> List[_Decl]:
    """Every class-attribute definition in one file — with the
    ``# guarded by:`` annotation when present (the shared declaration
    walk, guarded and unguarded sites alike)."""
    return [
        _Decl(
            attr, f.rel, node.name, lineno,
            m.group(1) if m else None, bool(m and m.group(2)),
        )
        for attr, node, lineno, m in iter_attr_decls(f)
    ]


class _AttrInfo(NamedTuple):
    resolvable: bool
    anyof: FrozenSet[str]  # locks, any one of which satisfies an access
    guard_files: FrozenSet[str]  # files declaring a guard (intra turf)
    decl: str  # human locator of one guarded declaration


def guard_table(project: Project) -> Dict[str, _AttrInfo]:
    """attr name -> project-wide guard info (see the module docstring's
    resolvability discipline).  Cached per run."""
    cached = project.cache.get("races_guard_table")
    if cached is not None:
        return cached  # type: ignore[return-value]
    decls: Dict[str, List[_Decl]] = {}
    for f in project.files:
        for d in _attr_decls(f):
            decls.setdefault(d.attr, []).append(d)
    table: Dict[str, _AttrInfo] = {}
    for attr, ds in decls.items():
        guarded = [d for d in ds if d.lock]
        if not guarded:
            continue
        # resolvable iff every DEFINING CLASS carries a guard for the
        # attr (per-class, not per-site: __init__ may assign a guarded
        # field a second time without re-annotating)
        classes = {(d.rel, d.klass) for d in ds}
        guarded_classes = {(d.rel, d.klass) for d in guarded}
        table[attr] = _AttrInfo(
            resolvable=classes == guarded_classes,
            anyof=frozenset(d.lock for d in guarded),
            guard_files=frozenset(d.rel for d in guarded),
            decl=f"{guarded[0].rel}:{guarded[0].line}",
        )
    project.cache["races_guard_table"] = table
    return table


# ---------------------------------------------------------------------------
# whole-program guarded-by: _locked call edges + cross-module fields
# ---------------------------------------------------------------------------


def _locked_defs(project: Project) -> Dict[str, List[Tuple[FileInfo, ast.AST]]]:
    """Every ``*_locked`` function definition, by name (nested defs
    included — the stop() drain helper is one)."""
    cached = project.cache.get("races_locked_defs")
    if cached is not None:
        return cached  # type: ignore[return-value]
    out: Dict[str, List[Tuple[FileInfo, ast.AST]]] = {}
    for f in project.files:
        for node in ast.walk(f.tree):
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)
            ) and node.name.endswith("_locked"):
                out.setdefault(node.name, []).append((f, node))
    project.cache["races_locked_defs"] = out
    return out


def _requirements(
    project: Project, name: str,
    _visiting: Optional[Set[str]] = None,
) -> List[FrozenSet[str]]:
    """The locks a ``*_locked`` helper's caller must hold: one any-of
    set per distinct guarded field the helper (transitively, through
    other ``*_locked`` calls) touches.  Empty when nothing resolves —
    the intraprocedural fallback (no finding)."""
    memo = project.cache.setdefault("races_locked_reqs", {})
    if name in memo:
        return memo[name]
    top = _visiting is None
    if top:
        _visiting = set()
    if name in _visiting:
        return []  # mutual recursion: the other frame owns the result
    _visiting.add(name)
    table = guard_table(project)
    defs = _locked_defs(project)
    sets: Set[FrozenSet[str]] = set()
    for f, node in defs.get(name, ()):
        for sub in ast.walk(node):
            if isinstance(sub, ast.Attribute):
                info = table.get(sub.attr)
                if info is None:
                    continue
                # the helper's own file's guards apply to it (the
                # intraprocedural semantics); project-resolvable
                # attrs apply everywhere
                if info.resolvable or f.rel in info.guard_files:
                    sets.add(info.anyof)
            elif isinstance(sub, ast.Call):
                callee = terminal_name(sub.func)
                if (
                    callee and callee != name
                    and callee.endswith("_locked") and callee in defs
                ):
                    for s in _requirements(project, callee, _visiting):
                        sets.add(s)
    _visiting.discard(name)
    out = sorted(sets, key=sorted)
    # memoize only complete (top-level) results: inside a traversal a
    # mutually recursive helper may have been cut short by the
    # _visiting check above, and caching that truncated set would
    # silently skip its lock requirements for the rest of the run
    if top:
        memo[name] = out
    return out


@rule
class RaceGuardedBy(Rule):
    """Whole-program guarded-by analysis: ``*_locked`` helpers are only
    called with their locks held, and resolvable annotated fields are
    checked across module boundaries (the intraprocedural
    ``lock-discipline`` rule stays as the fallback for unresolvable
    names)."""

    name = "race-guarded-by"
    summary = (
        "*_locked helpers are called with their (transitively "
        "required) locks held, and '# guarded by:' fields resolvable "
        "project-wide are checked across modules"
    )
    bug = "cross-module lock-discipline races the per-file rule misses"

    def check_project(self, project: Project):
        table = guard_table(project)
        defs = _locked_defs(project)
        for f in project.files:
            for node in ast.walk(f.tree):
                # -- _locked call discipline -------------------------
                if isinstance(node, ast.Call):
                    callee = terminal_name(node.func)
                    if (
                        callee and callee.endswith("_locked")
                        and callee in defs
                    ):
                        encl = enclosing_function(node)
                        fname = getattr(encl, "name", "")
                        if (
                            fname == "__init__"
                            or fname.endswith("_locked")
                        ):
                            continue  # the chain is checked at ITS callers
                        for req in _requirements(project, callee):
                            if not any(
                                _under_lock(node, lk) for lk in req
                            ):
                                locks = "/".join(sorted(req))
                                yield Finding(
                                    self.name, f.rel, node.lineno,
                                    node.col_offset,
                                    f"call to {callee}() without "
                                    f"holding {locks!r} — the _locked "
                                    "suffix is a caller-holds-the-lock "
                                    "contract; wrap the call in `with "
                                    f"*.{locks}:` or rename the helper",
                                )
                                break
                    continue
                # -- cross-module field accesses ---------------------
                if not isinstance(node, ast.Attribute):
                    continue
                info = table.get(node.attr)
                if info is None or not info.resolvable:
                    continue
                if f.rel in info.guard_files:
                    continue  # lock-discipline's (intraprocedural) turf
                encl = enclosing_function(node)
                fname = getattr(encl, "name", "")
                if fname == "__init__" or fname.endswith("_locked"):
                    continue
                if any(_under_lock(node, lk) for lk in info.anyof):
                    continue
                locks = "/".join(sorted(info.anyof))
                yield Finding(
                    self.name, f.rel, node.lineno, node.col_offset,
                    f"cross-module access to {node.attr!r} (guarded by "
                    f"{locks!r}, declared at {info.decl}) outside "
                    f"`with *.{locks}:` — take the lock, or suppress "
                    "with a justification if the lock-free read is "
                    "deliberate",
                )


# ---------------------------------------------------------------------------
# the static lock-order graph
# ---------------------------------------------------------------------------


class _LockDecl(NamedTuple):
    attr: str
    rel: str
    klass: Optional[str]  # None = module-level

    @property
    def node(self) -> str:
        mod = self.rel[:-3] if self.rel.endswith(".py") else self.rel
        mod = mod[len("slate_tpu/"):] if mod.startswith("slate_tpu/") else mod
        return (
            f"{mod}.{self.klass}.{self.attr}" if self.klass
            else f"{mod}.{self.attr}"
        )


def _is_lock_ctor(value: ast.AST) -> bool:
    if not isinstance(value, ast.Call):
        return False
    nm = terminal_name(value.func)
    if nm not in _LOCK_CTORS:
        return False
    root = value.func
    while isinstance(root, ast.Attribute):
        root = root.value
    if isinstance(root, ast.Name):
        return root.id in _LOCK_ROOTS or root.id in _LOCK_CTORS
    return False


class _GraphCtx:
    """Everything the graph walk needs, built once per project: lock
    declarations, per-file import maps, and function registries over
    the :data:`LOCK_SCOPE` files."""

    def __init__(self, project: Project):
        self.project = project
        self.files = [
            f for f in project.files
            if f.rel.startswith(LOCK_SCOPE)
        ]
        self.decls: List[_LockDecl] = []
        self.decl_by_attr: Dict[str, List[_LockDecl]] = {}
        # (rel, klass|None, attr) -> decl, for scoped resolution
        self.decl_scoped: Dict[Tuple[str, Optional[str], str], _LockDecl] = {}
        # function registries
        self.module_funcs: Dict[str, Dict[str, ast.AST]] = {}
        self.classes: Dict[str, Dict[str, ast.ClassDef]] = {}
        self.class_methods: Dict[
            Tuple[str, str], Dict[str, ast.AST]
        ] = {}
        self.methods_by_name: Dict[str, List[Tuple[FileInfo, str, ast.AST]]] = {}
        self.imports: Dict[str, Dict[str, Tuple[str, Optional[str]]]] = {}
        for f in self.files:
            self._scan_file(f)

    def _scan_file(self, f: FileInfo) -> None:
        rel = f.rel
        self.module_funcs[rel] = {}
        self.classes[rel] = {}
        self.imports[rel] = self._import_map(f)
        for node in f.tree.body:
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name) and _is_lock_ctor(
                        node.value
                    ):
                        self._add_decl(_LockDecl(tgt.id, rel, None))
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.module_funcs[rel][node.name] = node
            elif isinstance(node, ast.ClassDef):
                self.classes[rel][node.name] = node
                methods: Dict[str, ast.AST] = {}
                for sub in node.body:
                    if isinstance(
                        sub, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ):
                        methods[sub.name] = sub
                self.class_methods[(rel, node.name)] = methods
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Assign):
                        tgt = sub.targets[0]
                        if (
                            isinstance(tgt, ast.Attribute)
                            and isinstance(tgt.value, ast.Name)
                            and tgt.value.id == "self"
                            and _is_lock_ctor(sub.value)
                        ):
                            self._add_decl(
                                _LockDecl(tgt.attr, rel, node.name)
                            )
        for node in ast.walk(f.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                klass = self._owning_class(f, node)
                self.methods_by_name.setdefault(node.name, []).append(
                    (f, klass, node)
                )

    @staticmethod
    def _owning_class(f: FileInfo, node: ast.AST) -> Optional[str]:
        from .core import parents

        for anc in parents(node):
            if isinstance(anc, ast.ClassDef):
                return anc.name
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return None  # nested function: not a method
        return None

    def _add_decl(self, d: _LockDecl) -> None:
        key = (d.rel, d.klass, d.attr)
        if key in self.decl_scoped:
            return
        self.decl_scoped[key] = d
        self.decls.append(d)
        self.decl_by_attr.setdefault(d.attr, []).append(d)

    def _import_map(
        self, f: FileInfo
    ) -> Dict[str, Tuple[str, Optional[str]]]:
        """alias -> (target rel, member|None): module aliases map with
        member None; from-imported functions/classes carry the member
        name."""
        out: Dict[str, Tuple[str, Optional[str]]] = {}
        pkg_parts = f.rel.split("/")[:-1]  # the file's package dirs
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.ImportFrom):
                continue
            # ast.ImportFrom.level (relative-import depth), not the
            # overload controller's guarded field of the same name
            if node.level == 0:  # slate-lint: disable=race-guarded-by
                base = (node.module or "").split(".")
            else:
                base = pkg_parts[: len(pkg_parts) - (node.level - 1)]  # slate-lint: disable=race-guarded-by
                if node.module:
                    base = base + node.module.split(".")
            for alias in node.names:
                name = alias.asname or alias.name
                as_module = "/".join(base + [alias.name]) + ".py"
                if as_module in self.project.by_rel:
                    out[name] = (as_module, None)
                    continue
                as_member = "/".join(base) + ".py"
                if as_member in self.project.by_rel:
                    out[name] = (as_member, alias.name)
        return out

    # -- lock resolution ----------------------------------------------------

    def resolve_lock(
        self, expr: ast.AST, rel: str, klass: Optional[str]
    ) -> Optional[_LockDecl]:
        nm = terminal_name(expr)
        if nm is None:
            return None
        if isinstance(expr, ast.Name):
            d = self.decl_scoped.get((rel, None, nm))
            if d is not None:
                return d
        elif (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
            and klass is not None
        ):
            d = self.decl_scoped.get((rel, klass, nm))
            if d is not None:
                return d
        cands = self.decl_by_attr.get(nm, [])
        if len(cands) == 1:
            return cands[0]
        return None

    # -- call resolution ----------------------------------------------------

    def resolve_call(
        self, call: ast.Call, f: FileInfo, klass: Optional[str]
    ) -> Optional[Tuple[FileInfo, Optional[str], ast.AST]]:
        func = call.func
        if isinstance(func, ast.Name):
            nm = func.id
            node = self.module_funcs.get(f.rel, {}).get(nm)
            if node is not None:
                return (f, None, node)
            cls = self.classes.get(f.rel, {}).get(nm)
            if cls is not None:
                init = self.class_methods.get((f.rel, nm), {}).get("__init__")
                return (f, nm, init) if init is not None else None
            imp = self.imports.get(f.rel, {}).get(nm)
            if imp is not None:
                return self._resolve_member(imp)
            return None
        if not isinstance(func, ast.Attribute):
            return None
        nm = func.attr
        recv = func.value
        if isinstance(recv, ast.Name) and recv.id == "self" and klass:
            node = self.class_methods.get((f.rel, klass), {}).get(nm)
            if node is not None:
                return (f, klass, node)
        if isinstance(recv, ast.Name):
            imp = self.imports.get(f.rel, {}).get(recv.id)
            if imp is not None and imp[1] is None:
                target = self.project.by_rel.get(imp[0])
                if target is not None:
                    node = self.module_funcs.get(imp[0], {}).get(nm)
                    if node is not None:
                        return (target, None, node)
                    cls = self.classes.get(imp[0], {}).get(nm)
                    if cls is not None:
                        init = self.class_methods.get(
                            (imp[0], nm), {}
                        ).get("__init__")
                        if init is not None:
                            return (target, nm, init)
                return None
        # unique-name fallback, denylisted against container lookalikes
        if nm in _CALL_DENY:
            return None
        cands = self.methods_by_name.get(nm, [])
        if len(cands) == 1:
            return cands[0]
        return None

    def _resolve_member(
        self, imp: Tuple[str, Optional[str]]
    ) -> Optional[Tuple[FileInfo, Optional[str], ast.AST]]:
        rel, member = imp
        target = self.project.by_rel.get(rel)
        if target is None or member is None:
            return None
        node = self.module_funcs.get(rel, {}).get(member)
        if node is not None:
            return (target, None, node)
        if member in self.classes.get(rel, {}):
            init = self.class_methods.get((rel, member), {}).get("__init__")
            if init is not None:
                return (target, member, init)
        return None

    # -- transitive lock sets ----------------------------------------------

    def locks_of(
        self, f: FileInfo, klass: Optional[str], node: ast.AST,
        _visiting: Optional[Set[int]] = None,
    ) -> Set[str]:
        """Qualified locks ``node`` may acquire, transitively through
        resolvable calls (memoized; call-graph cycles are cut)."""
        memo = self.project.cache.setdefault("races_locksets", {})
        key = id(node)
        if key in memo:
            return memo[key]
        if _visiting is None:
            _visiting = set()
        if key in _visiting:
            return set()
        _visiting.add(key)
        out: Set[str] = set()
        for sub in ast.walk(node):
            if isinstance(sub, ast.With):
                for item in sub.items:
                    d = self.resolve_lock(item.context_expr, f.rel, klass)
                    if d is not None:
                        out.add(d.node)
            elif isinstance(sub, ast.Call):
                if (
                    isinstance(sub.func, ast.Attribute)
                    and sub.func.attr == "acquire"
                ):
                    d = self.resolve_lock(sub.func.value, f.rel, klass)
                    if d is not None:
                        out.add(d.node)
                    continue
                resolved = self.resolve_call(sub, f, klass)
                if resolved is not None and resolved[2] is not node:
                    out |= self.locks_of(*resolved, _visiting=_visiting)
        _visiting.discard(key)
        memo[key] = out
        return out


def _graph_ctx(project: Project) -> _GraphCtx:
    ctx = project.cache.get("races_graph_ctx")
    if ctx is None:
        ctx = project.cache["races_graph_ctx"] = _GraphCtx(project)
    return ctx


def lock_graph(project: Project) -> Dict[Tuple[str, str], str]:
    """The static acquisition-order graph over :data:`LOCK_SCOPE`:
    ``(held, acquired) -> "rel:line"`` provenance (first site, in
    deterministic file/line order).  An edge means: somewhere, the
    second lock is (possibly through calls) acquired while the first
    is held."""
    cached = project.cache.get("races_lock_graph")
    if cached is not None:
        return cached  # type: ignore[return-value]
    ctx = _graph_ctx(project)
    raw: List[Tuple[str, str, str, int]] = []  # (from, to, rel, line)
    for f in ctx.files:
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.With):
                continue
            encl = enclosing_function(node)
            klass = (
                ctx._owning_class(f, encl) if encl is not None else None
            )
            held = [
                d for d in (
                    ctx.resolve_lock(it.context_expr, f.rel, klass)
                    for it in node.items
                ) if d is not None
            ]
            if not held:
                continue
            # `with a, b:` is itself an ordering
            for i, a in enumerate(held):
                for b in held[i + 1:]:
                    if a.node != b.node:
                        raw.append((a.node, b.node, f.rel, node.lineno))
            for stmt in node.body:
                for sub in ast.walk(stmt):
                    acquired: Set[str] = set()
                    if isinstance(sub, ast.With):
                        for item in sub.items:
                            d = ctx.resolve_lock(
                                item.context_expr, f.rel, klass
                            )
                            if d is not None:
                                acquired.add(d.node)
                    elif isinstance(sub, ast.Call):
                        if (
                            isinstance(sub.func, ast.Attribute)
                            and sub.func.attr == "acquire"
                        ):
                            d = ctx.resolve_lock(
                                sub.func.value, f.rel, klass
                            )
                            if d is not None:
                                acquired.add(d.node)
                        else:
                            resolved = ctx.resolve_call(sub, f, klass)
                            if resolved is not None:
                                acquired |= ctx.locks_of(*resolved)
                    if not acquired:
                        continue
                    for a in held:
                        for b in acquired:
                            if a.node != b:
                                raw.append(
                                    (a.node, b, f.rel, sub.lineno)
                                )
    raw.sort(key=lambda e: (e[0], e[1], e[2], e[3]))
    edges: Dict[Tuple[str, str], str] = {}
    for a, b, rel, line in raw:
        edges.setdefault((a, b), f"{rel}:{line}")
    project.cache["races_lock_graph"] = edges
    return edges


def graph_cycles(
    edges: Dict[Tuple[str, str], str]
) -> List[List[str]]:
    """Cycles in the order graph (one representative per strongly
    connected component with >= 2 nodes), each as a node list."""
    adj: Dict[str, List[str]] = {}
    for a, b in edges:
        adj.setdefault(a, []).append(b)
        adj.setdefault(b, [])
    # Tarjan SCC, iterative
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    onstack: Set[str] = set()
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]

    for root in sorted(adj):
        if root in index:
            continue
        work = [(root, iter(adj[root]))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        onstack.add(root)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    onstack.add(w)
                    work.append((w, iter(adj[w])))
                    advanced = True
                    break
                if w in onstack:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                pv = work[-1][0]
                low[pv] = min(low[pv], low[v])
            if low[v] == index[v]:
                comp = []
                while True:
                    w = stack.pop()
                    onstack.discard(w)
                    comp.append(w)
                    if w == v:
                        break
                if len(comp) > 1:
                    sccs.append(sorted(comp))
    return sccs


def graph_to_doc(edges: Dict[Tuple[str, str], str]) -> dict:
    """The artifact shape ``LOCK_ORDER.json`` carries."""
    return {
        "version": 1,
        "edges": [
            {"from": a, "to": b, "via": via}
            for (a, b), via in sorted(edges.items())
        ],
    }


def load_graph_artifact(root: str) -> Optional[Set[Tuple[str, str]]]:
    """The checked-in graph's (from, to) pairs; None when absent."""
    path = os.path.join(root, LOCK_GRAPH_NAME)
    if not os.path.isfile(path):
        return None
    with open(path, encoding="utf-8") as fh:
        doc = json.load(fh)
    return {
        (e["from"], e["to"]) for e in doc.get("edges", ())
    }


def write_graph_artifact(root: str, project: Project) -> str:
    """Regenerate the checked-in artifact from the current tree."""
    path = os.path.join(root, LOCK_GRAPH_NAME)
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(graph_to_doc(lock_graph(project)), fh, indent=2)
        fh.write("\n")
    return path


@rule
class RaceLockOrder(Rule):
    """The static lock-order graph: a cycle is a potential deadlock,
    and — when the checked-in :data:`LOCK_GRAPH_NAME` artifact exists —
    every edge not in it is a reviewable finding (regenerate with
    ``tools/slate_lint.py --write-lock-graph`` after review)."""

    name = "race-lock-order"
    summary = (
        "the nested-lock acquisition graph over serve/+integrity/+aux/ "
        "is acyclic, and new edges vs the checked-in LOCK_ORDER.json "
        "show up as findings"
    )
    bug = "cross-module lock-order inversions no single file shows"

    def check_project(self, project: Project):
        edges = lock_graph(project)
        for comp in graph_cycles(edges):
            # anchor at the provenance of one edge inside the cycle
            via = None
            for (a, b), v in sorted(edges.items()):
                if a in comp and b in comp:
                    via = v
                    break
            rel, _, line = (via or "LOCK_ORDER.json:1").rpartition(":")
            yield Finding(
                self.name, rel or LOCK_GRAPH_NAME, int(line), 0,
                "lock-order cycle (potential deadlock): "
                + " <-> ".join(comp)
                + " — break the cycle or move one acquisition outside "
                "the other lock's region",
            )
        known = load_graph_artifact(project.root)
        if known is None:
            return  # no artifact in this tree (fixtures)
        for (a, b), via in sorted(edges.items()):
            if (a, b) in known:
                continue
            rel, _, line = via.rpartition(":")
            yield Finding(
                self.name, rel, int(line), 0,
                f"new lock-order edge {a} -> {b} not in "
                f"{LOCK_GRAPH_NAME} — review it for inversions against "
                "the shipped graph, then regenerate the artifact with "
                "tools/slate_lint.py --write-lock-graph",
            )
        stale = sorted(known - set(edges))
        if stale:
            pairs = ", ".join(f"{a} -> {b}" for a, b in stale[:4])
            more = f" (+{len(stale) - 4} more)" if len(stale) > 4 else ""
            yield Finding(
                self.name, LOCK_GRAPH_NAME, 1, 0,
                f"{LOCK_GRAPH_NAME} lists edges the tree no longer "
                f"has: {pairs}{more} — regenerate with "
                "tools/slate_lint.py --write-lock-graph",
            )
