"""slate-lint core: the AST engine, rule registry, suppressions, and
baseline semantics.

Every rule mechanizes an invariant this repo has had to re-police by
hand across PRs (see ``CHANGES.md``): ungated hot-path instrumentation,
metric-name drift between emitters and the ``tools/*_report.py`` joins,
traced-value misuse inside jitted code, enum/ndarray pytree hazards,
lock discipline in the threaded serve pool, env-var documentation
drift, and exception-context hygiene.  The framework is stdlib-only
(``ast`` + ``re``); rules never import the code under analysis, so a
lint run cannot be broken by (or mask) an import-time failure in the
tree it checks.

Vocabulary:

* **Finding** — one violation: rule name, repo-relative path, line/col,
  message.  Stable ``fingerprint()`` (rule + path + stripped source
  line, line-number free) keys the baseline so findings survive
  unrelated edits above them.
* **Suppression** — ``# slate-lint: disable=<rule>[,<rule>...]`` on the
  flagged line silences those rules there (``disable=all`` silences
  everything).  Suppressions are for *deliberate* violations (e.g. a
  documented lock-free racy read); each should carry a justification
  comment.
* **Baseline** — a checked-in JSON file of accepted legacy
  fingerprints (:data:`BASELINE_NAME`).  ``run()`` reports baselined
  findings separately and only *new* findings fail the gate.  The
  shipped tree carries an empty baseline: every true positive found by
  the first full-tree run was fixed, not grandfathered.

Rules register with :func:`rule`; they implement ``check_file`` (one
parsed file at a time) and/or ``check_project`` (cross-file joins:
metric drift, env drift, fault-site registry).  ``Project`` carries
every parsed file plus README text and a shared per-run cache so rules
can reuse expensive collections (e.g. the emitted-metric-name set).
"""

from __future__ import annotations

import ast
import hashlib
import json
import os
import re
import time
from dataclasses import dataclass
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Set

#: checked-in baseline of accepted legacy findings (repo root)
BASELINE_NAME = ".slate-lint-baseline.json"

_SUPPRESS_RE = re.compile(
    r"#\s*slate-lint:\s*disable=([A-Za-z0-9_\-]+(?:\s*,\s*[A-Za-z0-9_\-]+)*)"
)


@dataclass(frozen=True)
class Finding:
    """One rule violation at one source location."""

    rule: str
    path: str  # repo-relative, posix separators
    line: int
    col: int
    message: str

    def fingerprint(self, line_text: str = "", occurrence: int = 0) -> str:
        """Line-number-free identity for baseline matching: the rule,
        the file, the stripped source text of the flagged line, and the
        occurrence ordinal among identical lines — stable under edits
        elsewhere in the file, while a SECOND identical violation in
        the same file still reads as new (baselining one copy-paste
        instance must not grandfather every future clone)."""
        h = hashlib.sha1(
            f"{self.rule}|{self.path}|{line_text.strip()}|{occurrence}"
            .encode()
        )
        return h.hexdigest()[:16]

    def as_dict(self, fingerprint: str) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "fingerprint": fingerprint,
        }


class FileInfo:
    """One parsed source file: AST (parent-linked), raw lines, and the
    per-line suppression map."""

    __slots__ = ("path", "rel", "source", "lines", "tree", "suppress")

    def __init__(self, path: str, rel: str, source: str):
        self.path = path
        self.rel = rel
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=rel)
        link_parents(self.tree)
        self.suppress = scan_suppressions(self.lines)

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""


class Project:
    """Everything one lint run sees: parsed files, README, repo root,
    and a cross-rule cache for shared collections."""

    def __init__(self, root: str, files: List[FileInfo],
                 readme_rel: str = "README.md",
                 readme_text: Optional[str] = None):
        self.root = root
        self.files = files
        self.by_rel: Dict[str, FileInfo] = {f.rel: f for f in files}
        self.readme_rel = readme_rel
        self.readme_text = readme_text
        self.cache: Dict[str, object] = {}

    def readme_lines(self) -> List[str]:
        return (self.readme_text or "").splitlines()


class Rule:
    """Base rule.  Subclasses set ``name`` (kebab-case id used in
    suppressions and reports), ``summary`` (one line for ``--list``
    and the README table), and ``bug`` (the CHANGES.md bug class the
    rule mechanizes)."""

    name = ""
    summary = ""
    bug = ""

    def check_file(self, f: FileInfo, project: Project) -> Iterable[Finding]:
        return ()

    def check_project(self, project: Project) -> Iterable[Finding]:
        return ()


#: the registry: rule name -> instance (populated by the @rule decorator)
RULES: Dict[str, Rule] = {}


def rule(cls):
    """Class decorator: instantiate and register one rule."""
    inst = cls()
    if not inst.name:
        raise ValueError(f"rule {cls.__name__} has no name")
    if inst.name in RULES:
        raise ValueError(f"duplicate rule name {inst.name!r}")
    RULES[inst.name] = inst
    return cls


# ---------------------------------------------------------------------------
# AST helpers shared by the rules
# ---------------------------------------------------------------------------


def link_parents(tree: ast.AST) -> None:
    """Attach ``.slate_parent`` to every node (rules walk ancestors for
    gating/with-block/except-handler context)."""
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child.slate_parent = node  # type: ignore[attr-defined]


def parents(node: ast.AST) -> Iterator[ast.AST]:
    cur = getattr(node, "slate_parent", None)
    while cur is not None:
        yield cur
        cur = getattr(cur, "slate_parent", None)


def enclosing_function(node: ast.AST) -> Optional[ast.AST]:
    for anc in parents(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return anc
    return None


def in_except_handler(node: ast.AST) -> bool:
    return any(isinstance(a, ast.ExceptHandler) for a in parents(node))


def terminal_name(node: ast.AST) -> Optional[str]:
    """The last dotted component of a Name/Attribute chain
    (``lax.while_loop`` -> ``while_loop``), else None."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def root_name(node: ast.AST) -> Optional[str]:
    """The first dotted component (``np.linalg.norm`` -> ``np``)."""
    while isinstance(node, ast.Attribute):
        node = node.value
    if isinstance(node, ast.Name):
        return node.id
    return None


def const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def fstring_prefix(node: ast.AST) -> Optional[str]:
    """Leading constant text of an f-string (None for plain nodes);
    empty string when the f-string starts with a formatted value."""
    if not isinstance(node, ast.JoinedStr):
        return None
    out = []
    for part in node.values:
        if isinstance(part, ast.Constant) and isinstance(part.value, str):
            out.append(part.value)
        else:
            break
    return "".join(out)


def scan_suppressions(lines: Sequence[str]) -> Dict[int, Set[str]]:
    """Per-line ``# slate-lint: disable=`` rule sets (1-based lines)."""
    out: Dict[int, Set[str]] = {}
    for i, line in enumerate(lines, 1):
        m = _SUPPRESS_RE.search(line)
        if m:
            out[i] = {part.strip() for part in m.group(1).split(",")}
    return out


# ---------------------------------------------------------------------------
# file discovery
# ---------------------------------------------------------------------------

#: directories whose .py files a full run lints
LINT_DIRS = ("slate_tpu", "tools")

_SKIP_PARTS = {"__pycache__", ".git"}


def discover(root: str) -> List[str]:
    """Repo-relative paths of every lintable .py file under
    :data:`LINT_DIRS` (sorted, deterministic)."""
    out: List[str] = []
    for top in LINT_DIRS:
        base = os.path.join(root, top)
        if not os.path.isdir(base):
            continue
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames if d not in _SKIP_PARTS]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    rel = os.path.relpath(os.path.join(dirpath, fn), root)
                    out.append(rel.replace(os.sep, "/"))
    return sorted(out)


def load_project(root: str,
                 rels: Optional[Sequence[str]] = None) -> "LoadResult":
    """Parse the tree into a :class:`Project`; syntax errors become
    ``parse-error`` findings instead of aborting the run."""
    if rels is None:
        rels = discover(root)
    files: List[FileInfo] = []
    errors: List[Finding] = []
    for rel in rels:
        path = os.path.join(root, rel)
        try:
            with open(path, encoding="utf-8") as fh:
                src = fh.read()
        except OSError as e:
            errors.append(Finding("parse-error", rel, 1, 0, f"unreadable: {e}"))
            continue
        try:
            files.append(FileInfo(path, rel, src))
        except SyntaxError as e:
            errors.append(Finding(
                "parse-error", rel, int(e.lineno or 1), int(e.offset or 0),
                f"syntax error: {e.msg}",
            ))
    readme_text = None
    readme_path = os.path.join(root, "README.md")
    if os.path.isfile(readme_path):
        with open(readme_path, encoding="utf-8") as fh:
            readme_text = fh.read()
    return LoadResult(Project(root, files, readme_text=readme_text), errors)


@dataclass
class LoadResult:
    project: Project
    errors: List[Finding]


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------


def load_baseline(path: str) -> Set[str]:
    """Fingerprint set from a baseline file (empty when absent)."""
    if not os.path.isfile(path):
        return set()
    with open(path, encoding="utf-8") as fh:
        data = json.load(fh)
    # fingerprints is a {fp: human-locator} map; iteration yields keys
    return set(data.get("fingerprints", {}))


def write_baseline(path: str, result: "LintResult") -> None:
    """Accept the run's current findings as the new baseline (the
    fingerprint maps to a human-readable locator so reviews of the
    baseline file mean something)."""
    fps = {}
    for fnd, fp in result.all_with_fingerprints:
        fps[fp] = f"{fnd.rule} {fnd.path}:{fnd.line}"
    payload = {"version": 1, "fingerprints": dict(sorted(fps.items()))}
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=2, sort_keys=True)
        fh.write("\n")


# ---------------------------------------------------------------------------
# the run
# ---------------------------------------------------------------------------


@dataclass
class LintResult:
    findings: List[Finding]  # new (unsuppressed, unbaselined)
    baselined: int
    suppressed: int
    files: int
    duration_s: float
    all_with_fingerprints: List  # [(Finding, fingerprint)] incl. baselined

    @property
    def ok(self) -> bool:
        return not self.findings

    def to_json(self) -> dict:
        fp_of = dict((id(f), fp) for f, fp in self.all_with_fingerprints)
        return {
            # schema_version advances whenever the report shape or the
            # rule set changes incompatibly (2: the race-guarded-by /
            # race-lock-order rules joined the registry) so report
            # consumers can detect the format; "version" stays for
            # pre-schema_version readers
            "schema_version": 2,
            "version": 1,
            "ok": self.ok,
            "files": self.files,
            "duration_s": round(self.duration_s, 3),
            "counts": {
                "new": len(self.findings),
                "baselined": self.baselined,
                "suppressed": self.suppressed,
            },
            "findings": [
                f.as_dict(fp_of.get(id(f), "")) for f in self.findings
            ],
        }

    def render(self) -> str:
        out = []
        for f in self.findings:
            out.append(f"{f.path}:{f.line}:{f.col}: {f.rule}: {f.message}")
        tally = (
            f"slate-lint: {len(self.findings)} finding(s), "
            f"{self.baselined} baselined, {self.suppressed} suppressed, "
            f"{self.files} files in {self.duration_s:.2f}s"
        )
        out.append(tally)
        return "\n".join(out)


def run(root: str,
        rules: Optional[Sequence[str]] = None,
        baseline: Optional[Set[str]] = None,
        rels: Optional[Sequence[str]] = None) -> LintResult:
    """Lint the tree under ``root`` with the named rules (default all),
    applying inline suppressions and the baseline fingerprint set."""
    t0 = time.perf_counter()
    unknown = sorted(set(rules or ()) - set(RULES))
    if unknown:
        raise ValueError(
            f"unknown rule(s) {', '.join(unknown)}; "
            f"known: {', '.join(sorted(RULES))}"
        )
    loaded = load_project(root, rels=rels)
    project = loaded.project
    active = [RULES[n] for n in (rules or sorted(RULES))]
    raw: List[Finding] = list(loaded.errors)
    for r in active:
        for f in project.files:
            raw.extend(r.check_file(f, project))
        raw.extend(r.check_project(project))
    raw.sort(key=lambda f: (f.path, f.line, f.col, f.rule, f.message))

    baseline = baseline or set()
    new: List[Finding] = []
    with_fp: List = []
    suppressed = 0
    baselined = 0
    occurrences: Dict[tuple, int] = {}
    for fnd in raw:
        fi = project.by_rel.get(fnd.path)
        if fi is not None:
            line_text = fi.line_text(fnd.line)
        elif fnd.path == project.readme_rel:
            lines = project.readme_lines()
            line_text = lines[fnd.line - 1] if 0 < fnd.line <= len(lines) else ""
        else:
            line_text = ""
        # the ordinal advances for EVERY finding, suppressed included:
        # adding a disable-comment on one of several identical lines
        # must not shift its baselined twins' fingerprints
        okey = (fnd.rule, fnd.path, line_text.strip())
        k = occurrences.get(okey, 0)
        occurrences[okey] = k + 1
        if fi is not None:
            sup = fi.suppress.get(fnd.line, ())
            if "all" in sup or fnd.rule in sup:
                suppressed += 1
                continue
        fp = fnd.fingerprint(line_text, k)
        with_fp.append((fnd, fp))
        if fp in baseline:
            baselined += 1
            continue
        new.append(fnd)
    return LintResult(
        findings=new, baselined=baselined, suppressed=suppressed,
        files=len(project.files), duration_s=time.perf_counter() - t0,
        all_with_fingerprints=with_fp,
    )
