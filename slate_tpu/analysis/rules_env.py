"""Environment-variable documentation drift: every ``SLATE_TPU_*``
knob the library reads appears in README's env tables, and every
documented knob still exists in code.

Bug class mechanized (CHANGES.md): multiple PRs shipped a new
``SLATE_TPU_*`` env var (or renamed one) and the README table was
reconciled only in a later review pass — an operator reading the docs
either misses a real knob or sets one that no longer does anything.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Set, Tuple

from .core import Finding, Project, Rule, const_str, rule

_ENV_RE = re.compile(r"^SLATE_TPU_[A-Z0-9_]+$")
_README_ENV_RE = re.compile(r"SLATE_TPU_[A-Z0-9_]+")


def _code_vars(project: Project) -> Dict[str, Tuple[str, int]]:
    """env var -> first (path, line) where a string literal names it."""
    out: Dict[str, Tuple[str, int]] = {}
    for f in project.files:
        for node in ast.walk(f.tree):
            s = const_str(node)
            if s is not None and _ENV_RE.match(s) and s not in out:
                out[s] = (f.rel, node.lineno)
    return out


@rule
class EnvDrift(Rule):
    """``SLATE_TPU_*`` reads vs. the README env tables, both ways."""

    name = "env-drift"
    summary = (
        "SLATE_TPU_* vars read under slate_tpu/ are documented in "
        "README, and documented vars still exist in code"
    )
    bug = "undocumented (or zombie-documented) SLATE_TPU_* knobs"

    def check_project(self, project: Project):
        if project.readme_text is None:
            return  # no README in this tree (fixtures opt in by adding one)
        code = _code_vars(project)
        documented: Set[str] = set(
            _README_ENV_RE.findall(project.readme_text)
        )
        for var, (rel, line) in sorted(code.items()):
            if not rel.startswith("slate_tpu/"):
                continue  # tools may reference vars docs cover elsewhere
            if var not in documented:
                yield Finding(
                    self.name, rel, line, 0,
                    f"{var} is read here but absent from README's env "
                    "tables — document the knob (or delete it)",
                )
        readme_lines = project.readme_lines()
        seen: Set[str] = set()
        for lineno, text in enumerate(readme_lines, 1):
            for m in _README_ENV_RE.finditer(text):
                var = m.group(0)
                if var in code or var in seen:
                    continue
                seen.add(var)
                yield Finding(
                    self.name, project.readme_rel, lineno, m.start(),
                    f"README documents {var} but no code reads it — "
                    "stale knob (renamed or removed)",
                )
