"""Metric-plane rules: name drift (emitters vs. report joins vs.
README) and the zero-overhead hot-path gating contract.

Bug classes mechanized (CHANGES.md):

* A report tool joining a metric name nothing emits renders the column
  silently as zero — the chaos/latency/tenant reports have each needed
  a review pass to catch a renamed counter.
* ``aux/metrics`` / ``aux/spans`` / ``aux/devmon`` are internally
  gated (one bool per call), but **argument construction is not**: an
  f-string metric name or a helper call in the argument list runs even
  with the subsystem off, which is exactly the "zero overhead when
  disabled" contract the serve hot path documents.  Several PRs have
  had review passes move such calls behind ``is_on()``.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from .core import (
    FileInfo,
    Finding,
    Project,
    Rule,
    const_str,
    enclosing_function,
    fstring_prefix,
    in_except_handler,
    parents,
    root_name,
    rule,
    terminal_name,
)

#: metric-registry entry points whose first argument is a metric name
METRIC_FNS = ("inc", "gauge", "observe", "observe_hist", "record_cost")

#: name families the drift rule reasons about — a string is treated as
#: a metric name only under one of these roots, so ordinary literals
#: never enter the join
METRIC_ROOTS = (
    "serve.", "faults.", "jit.", "precision.", "fallbacks.",
    "refine.", "transfer.", "stedc.", "devmon.", "soak.", "scale.",
    "factor.", "fleet.", "fabric.",
)

#: files whose string literals must never feed the emitted set (the
#: linter's own rule tables mention metric roots)
_ANALYSIS_PREFIX = "slate_tpu/analysis/"

#: README metric tokens ("devmon." is excluded: the README references
#: devmon *functions* far more than its one metric).  The lookbehind
#: keeps dotted import paths (slate_tpu.serve.placement) from matching
#: at their inner segments.
_README_TOKEN_RE = re.compile(
    r"(?<![.\w])(?:serve|faults|jit|precision|fallbacks|refine|transfer|"
    r"stedc|soak|scale|factor|fleet|fabric)\.[A-Za-z0-9_.{}<>,*]+"
)


def _is_metric(name: str) -> bool:
    return name.startswith(METRIC_ROOTS)


def _fstring_suffix(node: ast.AST) -> Optional[str]:
    """Trailing constant text of an f-string that STARTS with a
    formatted value (``f"{name}.calls"`` -> ``".calls"``)."""
    if not isinstance(node, ast.JoinedStr) or not node.values:
        return None
    if not isinstance(node.values[0], ast.FormattedValue):
        return None
    out = []
    for part in reversed(node.values):
        if isinstance(part, ast.Constant) and isinstance(part.value, str):
            out.append(part.value)
        else:
            break
    return "".join(reversed(out)) or None


def emitted_metrics(project: Project) -> Tuple[Set[str], Set[str], Set[str]]:
    """(exact, prefix, suffix) metric-name sets emitted under
    ``slate_tpu/``.

    Exact names come from string constants, prefixes from f-strings'
    leading constant run, suffixes from f-strings built over a computed
    base (``f"{name}.calls"`` with ``name = f"refine.{routine}"``).
    Collection covers *all* literals under the metric roots, not just
    direct ``metrics.*`` call sites, because emitters legitimately
    precompute names (``self.q_gauge = f"serve.replica.{n}.queue_depth"``).
    A BARE root prefix (``f"serve.{label}..."``) is excluded — it would
    make every serve.* name match and the whole rule vacuous.  Cached
    per run (rule 2 reuses it for recovery-counter validation)."""
    cached = project.cache.get("emitted_metrics")
    if cached is not None:
        return cached  # type: ignore[return-value]
    exact: Set[str] = set()
    prefix: Set[str] = set()
    suffix: Set[str] = set()
    for f in project.files:
        if not f.rel.startswith("slate_tpu/"):
            continue
        if f.rel.startswith(_ANALYSIS_PREFIX):
            continue
        for node in ast.walk(f.tree):
            s = const_str(node)
            if s is not None and _is_metric(s):
                # a recovery counter named inside the fault-site
                # registry is a CONSUMER, not an emitter — counting it
                # here would make rule 2's ghost-counter check vacuous
                if any(
                    isinstance(a, ast.Call)
                    and terminal_name(a.func) == "SiteSpec"
                    for a in parents(node)
                ):
                    continue
                exact.add(s)
                continue
            p = fstring_prefix(node)
            if p and _is_metric(p) and p not in METRIC_ROOTS:
                prefix.add(p)
            suf = _fstring_suffix(node)
            if suf and suf.startswith("."):
                suffix.add(suf)
    out = (exact, prefix, suffix)
    project.cache["emitted_metrics"] = out
    return out


def _matches(name: str, is_prefix: bool, exact: Set[str],
             prefixes: Set[str], suffixes: Set[str] = frozenset()) -> bool:
    if is_prefix:
        return (
            any(e.startswith(name) for e in exact)
            or any(p.startswith(name) or name.startswith(p)
                   for p in prefixes)
        )
    return (
        name in exact
        or any(name.startswith(p) for p in prefixes)
        or any(name.endswith(s) for s in suffixes)
    )


@rule
class MetricDrift(Rule):
    """Every metric name a report tool joins (and every name the README
    documents) must be emitted somewhere under ``slate_tpu/``."""

    name = "metric-drift"
    summary = (
        "metric names consumed by tools/*_report.py or listed in README "
        "must have an emitter under slate_tpu/"
    )
    bug = "stale counter names silently rendering as zero in report joins"

    def check_project(self, project: Project):
        exact, prefixes, suffixes = emitted_metrics(project)
        if not exact and not prefixes:
            return  # nothing emits: a fixture tree without emitters
        for f in project.files:
            if not (f.rel.startswith("tools/")
                    and f.rel.endswith("_report.py")):
                continue
            for node in ast.walk(f.tree):
                s = const_str(node)
                is_prefix = False
                if s is None:
                    s = fstring_prefix(node)
                    if not s:
                        continue
                    is_prefix = True
                if not _is_metric(s):
                    continue
                if s.endswith((".py", ".md", ".json", ".jsonl")):
                    continue  # a file path, not a metric name
                # a literal ending in "." is a prefix probe by
                # construction (the tools use them with startswith)
                if s.endswith("."):
                    is_prefix = True
                if not _matches(s, is_prefix, exact, prefixes, suffixes):
                    yield Finding(
                        self.name, f.rel, node.lineno, node.col_offset,
                        f"metric {s!r} is joined here but nothing under "
                        "slate_tpu/ emits it (renamed or misspelled? "
                        "the report column reads as zero)",
                    )
        # README direction: documented names must be emitted
        for lineno, line in enumerate(project.readme_lines(), 1):
            for m in _README_TOKEN_RE.finditer(line):
                tok = m.group(0).rstrip(".,")
                if tok.endswith((".py", ".md", ".json", ".jsonl")):
                    continue  # a file path, not a metric name
                if line[m.end():m.end() + 1] == "(":
                    continue  # a function reference, not a metric name
                if tok.lower() != tok:
                    continue  # class reference (serve.Rejected): metric
                    # names in this tree are all lowercase
                # placeholder segments (<i>, {h2d,d2h}, *) make the
                # token a family: match it as a prefix up to the first
                # placeholder
                cut = len(tok)
                for ch in "<{*":
                    i = tok.find(ch)
                    if i != -1:
                        cut = min(cut, i)
                is_prefix = cut < len(tok)
                name = tok[:cut]
                if not _is_metric(name):
                    continue
                if not _matches(name, is_prefix, exact, prefixes, suffixes):
                    yield Finding(
                        self.name, project.readme_rel, lineno, m.start(),
                        f"README documents metric {tok!r} but nothing "
                        "under slate_tpu/ emits it",
                    )


# ---------------------------------------------------------------------------
# zero-overhead gating
# ---------------------------------------------------------------------------

#: observability namespaces and the helper calls the gating rule covers
_GATED_MODS: Dict[str, Tuple[str, ...]] = {
    "metrics": METRIC_FNS,
    "spans": ("start", "end", "event", "record", "annotate", "span"),
    "devmon": ("sample_devices", "capture_jitted", "roofline"),
}

#: calls considered free to evaluate as arguments (O(1) builtins)
_CHEAP_CALLS = {
    "len", "int", "float", "str", "bool", "min", "max", "round", "abs",
    "sorted", "enumerate", "zip", "range", "sum", "repr", "type", "id",
    "tuple", "list", "dict", "set", "getattr", "isinstance",
}


def _costly_args(call: ast.Call) -> Optional[ast.AST]:
    """First argument subexpression that does real work at call time
    (an f-string render or a non-builtin call), else None."""
    for arg in list(call.args) + [kw.value for kw in call.keywords]:
        for node in ast.walk(arg):
            if isinstance(node, ast.JoinedStr) and any(
                isinstance(v, ast.FormattedValue) for v in node.values
            ):
                return node
            if isinstance(node, ast.Call):
                t = terminal_name(node.func)
                if t not in _CHEAP_CALLS:
                    return node
    return None


def _gate_aliases(func: ast.AST) -> Set[str]:
    """Names assigned from an ``is_on()``-bearing expression in this
    function (``mon = metrics.is_on()``, ``tracked = metrics.is_on()
    and ...``)."""
    out: Set[str] = set()
    for node in ast.walk(func):
        if not isinstance(node, ast.Assign):
            continue
        if not any(
            isinstance(c, ast.Call) and terminal_name(c.func) == "is_on"
            for c in ast.walk(node.value)
        ):
            continue
        for tgt in node.targets:
            if isinstance(tgt, ast.Name):
                out.add(tgt.id)
    return out


def _spanish(name: Optional[str]) -> bool:
    """Does the name look like a span object / trace id binding?"""
    if not name:
        return False
    low = name.lower()
    return "span" in low or "trace" in low or low in ("root", "_root", "csp")


def _contains(tree: ast.AST, node: ast.AST) -> bool:
    return any(d is node for d in ast.walk(tree))


def _early_return_gated(encl: ast.AST, call: ast.Call,
                        aliases: Set[str]) -> bool:
    """Early-return gating: an ``if not <gate>: return`` (or continue/
    raise) earlier in the enclosing function body covers everything
    after it — the ``_capture_cost`` idiom.  A call INSIDE the guard's
    own body runs exactly when the gate is off and is never covered."""
    body = getattr(encl, "body", None)
    if not isinstance(body, list):
        return False
    for stmt in body:
        if stmt.lineno >= call.lineno:
            break
        if not isinstance(stmt, ast.If):
            continue
        test = stmt.test
        if not (isinstance(test, ast.UnaryOp)
                and isinstance(test.op, ast.Not)):
            continue
        if not _test_gates(test.operand, aliases, False):
            continue
        if _contains(stmt, call):
            continue  # the call IS the gate's off-path body
        if any(
            isinstance(s, (ast.Return, ast.Continue, ast.Raise))
            for s in stmt.body
        ):
            return True
    return False


def _test_gates(test: ast.AST, aliases: Set[str], allow_none: bool) -> bool:
    for node in ast.walk(test):
        if isinstance(node, ast.Call) and terminal_name(node.func) == "is_on":
            return True
        if isinstance(node, ast.Name) and node.id in aliases:
            return True
        if allow_none and isinstance(node, ast.Compare) and any(
            isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops
        ) and _spanish(terminal_name(node.left)):
            # span objects/trace ids are only allocated while tracing is
            # on, so `req.span is not None` is an armed-flag proxy
            return True
    return False


@rule
class HotPathGating(Rule):
    """On serve hot paths, observability calls whose *arguments* cost
    something (f-string names, helper calls) must sit behind the
    subsystem's armed-flag gate — the registry's internal bool fires
    after the arguments were already built."""

    name = "hot-path-gating"
    summary = (
        "serve-path metrics/spans/devmon calls with costly arguments "
        "must be behind is_on() (or an alias / span-presence check)"
    )
    bug = "ungated hot-path instrumentation breaking zero-overhead-off"

    scope_prefix = "slate_tpu/serve/"

    def check_file(self, f: FileInfo, project: Project):
        if not f.rel.startswith(self.scope_prefix):
            return
        alias_cache: Dict[int, Set[str]] = {}
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if not isinstance(func, ast.Attribute):
                continue
            mod = root_name(func.value)
            fns = _GATED_MODS.get(mod or "")
            if not fns or func.attr not in fns:
                continue
            costly = _costly_args(node)
            if costly is None:
                continue
            encl = enclosing_function(node)
            if encl is None:
                continue  # import-time code is not a hot path
            if in_except_handler(node):
                continue  # failure paths are cold by definition
            aliases = alias_cache.get(id(encl))
            if aliases is None:
                aliases = alias_cache[id(encl)] = _gate_aliases(encl)
            allow_none = mod == "spans"
            gated = _early_return_gated(encl, node, aliases)
            if not gated:
                for anc in parents(node):
                    if anc is encl:
                        break
                    if not isinstance(anc, (ast.If, ast.IfExp)):
                        continue
                    test = anc.test
                    # polarity + branch membership matter: the ON
                    # branch of a positive gate is covered, the OFF
                    # branch (else of is_on(), body of `not mon`) runs
                    # exactly when the subsystem is disarmed
                    negated = (
                        isinstance(test, ast.UnaryOp)
                        and isinstance(test.op, ast.Not)
                    )
                    inner = test.operand if negated else test
                    if not _test_gates(inner, aliases, allow_none):
                        continue
                    body = (
                        anc.body if isinstance(anc.body, list)
                        else [anc.body]
                    )
                    in_body = any(_contains(s, node) for s in body)
                    if in_body != negated:
                        gated = True
                        break
            if not gated:
                yield Finding(
                    self.name, f.rel, node.lineno, node.col_offset,
                    f"{mod}.{func.attr}(...) builds its arguments "
                    "unconditionally (f-string or helper call at line "
                    f"{costly.lineno}); gate it behind "
                    f"{mod}.is_on() so the off state stays one bool",
                )
