"""Fault-site registry rule: every chaos call site is declared, every
declared site is recoverable (or explicitly informational), and every
recovery counter really exists.

Bug class mechanized (CHANGES.md): the chaos layer's site list, the
call sites threaded through serve/, and ``tools/chaos_report.py``'s
site -> recovery-counter join were three hand-kept copies of the same
map — a site added to one but not the others either never injects,
or injects and can never show recovery (a permanent CI flag), or joins
counters nothing emits (recovery silently reads zero).  The registry
in ``aux/faults.py`` (``SITE_SPECS``) is now the single source of
truth — ``chaos_report`` derives its map from it at runtime, and this
rule checks the remaining drift directions statically.
"""

from __future__ import annotations

import ast
from typing import Dict, List, NamedTuple, Optional, Tuple

from .core import (
    FileInfo,
    Finding,
    Project,
    Rule,
    const_str,
    root_name,
    rule,
    terminal_name,
)
from .rules_metrics import _matches, emitted_metrics

_FAULTS_REL = "slate_tpu/aux/faults.py"

#: faults entry points whose first argument names a site
_SITE_FNS = ("check", "fire", "sleep", "corrupt", "perturb", "poison_info")


class SiteSpec(NamedTuple):
    name: str
    recovery: Tuple[str, ...]
    informational: bool
    line: int


def parse_site_specs(tree: ast.AST) -> Dict[str, SiteSpec]:
    """Extract every ``SiteSpec("<name>", recovery=(...),
    informational=...)`` literal from a parsed faults.py — the ONE
    registry extractor, shared by the lint rule (via
    :func:`site_registry`) and ``tools/chaos_report.py`` (which loads
    this module by file path to stay independent of the library's
    importability)."""
    out: Dict[str, SiteSpec] = {}
    for node in ast.walk(tree):
        if not (
            isinstance(node, ast.Call)
            and terminal_name(node.func) == "SiteSpec"
            and node.args
        ):
            continue
        name = const_str(node.args[0])
        if name is None:
            continue
        recovery: Tuple[str, ...] = ()
        informational = False
        for kw in node.keywords:
            if kw.arg == "recovery" and isinstance(
                kw.value, (ast.Tuple, ast.List)
            ):
                recovery = tuple(
                    s for s in (const_str(e) for e in kw.value.elts)
                    if s is not None
                )
            elif kw.arg == "informational" and isinstance(
                kw.value, ast.Constant
            ):
                informational = bool(kw.value.value)
        out[name] = SiteSpec(name, recovery, informational, node.lineno)
    return out


def site_registry(project: Project) -> Optional[Dict[str, SiteSpec]]:
    """The parsed SITE_SPECS registry of this project's aux/faults.py;
    None when the file (or the registry) is absent — fixture trees."""
    cached = project.cache.get("site_registry")
    if cached is not None:
        return cached  # type: ignore[return-value]
    f = project.by_rel.get(_FAULTS_REL)
    if f is None:
        return None
    out = parse_site_specs(f.tree)
    if not out:
        return None
    project.cache["site_registry"] = out
    return out


@rule
class FaultSiteRegistry(Rule):
    """Chaos call sites vs. the aux/faults.py SITE_SPECS registry (the
    single source chaos_report derives its recovery join from)."""

    name = "fault-site"
    summary = (
        "faults.check/fire/... sites are declared in SITE_SPECS with a "
        "recovery family (or informational) whose counters are emitted"
    )
    bug = "hand-kept site/recovery maps drifting across three files"

    def check_project(self, project: Project):
        registry = site_registry(project)
        if registry is None:
            return  # no registry in this tree (fixtures)
        # direction 1: every call site names a declared site
        for f in project.files:
            if not f.rel.startswith("slate_tpu/") or f.rel == _FAULTS_REL:
                continue
            for node in ast.walk(f.tree):
                if not (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and root_name(node.func.value) == "faults"
                    and node.func.attr in _SITE_FNS
                    and node.args
                ):
                    continue
                site = const_str(node.args[0])
                if site is None:
                    continue  # dynamic site names are out of scope
                if site not in registry:
                    yield Finding(
                        self.name, f.rel, node.lineno, node.col_offset,
                        f"fault site {site!r} is not declared in "
                        "aux/faults.py SITE_SPECS — it can be armed but "
                        "chaos_report has no recovery family for it",
                    )
        # direction 2: every declared site is recoverable or
        # explicitly informational, and its counters are real (exact
        # or specific-prefix emitters only: a recovery family joined
        # on a computed-base suffix would be unverifiable)
        exact, prefixes, _suffixes = emitted_metrics(project)
        for spec in registry.values():
            if not spec.recovery and not spec.informational:
                yield Finding(
                    self.name, _FAULTS_REL, spec.line, 0,
                    f"site {spec.name!r} declares no recovery counters "
                    "and is not informational — an injection here can "
                    "never show containment in chaos_report",
                )
            for counter in spec.recovery:
                if not _matches(counter, False, exact, prefixes):
                    yield Finding(
                        self.name, _FAULTS_REL, spec.line, 0,
                        f"site {spec.name!r} joins recovery counter "
                        f"{counter!r} but nothing under slate_tpu/ "
                        "emits it (the chaos report would flag the "
                        "site forever)",
                    )
