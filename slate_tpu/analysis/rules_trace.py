"""JAX tracing-safety rules: traced-value misuse inside staged
functions, and pytree hazards (enum-keyed dicts, ndarray-field
dataclasses with a generated ``__eq__``).

Bug classes mechanized (CHANGES.md):

* PR1's ``shard_map`` collection kill and several review passes since:
  host-side control flow (``if``/``while``), ``bool()/int()/float()``
  coercions, or ``np.*`` host calls on traced operands inside a
  ``jit``/``lax.while_loop``/``lax.cond``/``shard_map`` body either
  crash at trace time or silently constant-fold one trace's value into
  the compiled program.
* PR3's unorderable-enum pytree crash: a dict keyed by enum members
  reaching a jax API makes pytree flattening sort the keys and raise.
* PR12's ``_Request`` fix: a ``@dataclass`` with ndarray-typed fields
  generates an ``__eq__`` that compares arrays — truthiness raises, and
  "equal" requests could alias.  ``eq=False`` (identity semantics) is
  the contract for array-carrying dataclasses.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .core import (
    FileInfo,
    Finding,
    Project,
    Rule,
    const_str,
    parents,
    root_name,
    rule,
    terminal_name,
)

#: callables that stage their function argument(s) for tracing
_TRACE_WRAPPERS = {"jit", "gated_jit", "instrument_jit"}
_TRACE_HOFS = {
    "while_loop", "cond", "scan", "fori_loop", "shard_map", "checkpoint",
    "vmap", "pmap", "switch",
}

#: numpy module aliases (host-side: a call on a traced operand forces a
#: transfer or crashes under trace)
_NP_ROOTS = {"np", "numpy"}


def _is_trace_decorator(dec: ast.AST) -> bool:
    t = terminal_name(dec)
    if t in _TRACE_WRAPPERS:
        return True
    if isinstance(dec, ast.Call):
        t = terminal_name(dec.func)
        if t in _TRACE_WRAPPERS:
            return True
        if t == "partial" and dec.args and (
            terminal_name(dec.args[0]) in _TRACE_WRAPPERS
        ):
            return True
    return False


def _static_params(dec: ast.AST, fn: ast.AST) -> Set[str]:
    """Parameter names a jit decorator marks static
    (``static_argnames=(...)`` / ``static_argnums=(...)``): those are
    Python values under the trace, not traced operands."""
    if not isinstance(dec, ast.Call):
        return set()
    out: Set[str] = set()
    pos = [p.arg for p in fn.args.posonlyargs + fn.args.args]
    for kw in dec.keywords:
        vals = (
            kw.value.elts if isinstance(kw.value, (ast.Tuple, ast.List))
            else [kw.value]
        )
        if kw.arg == "static_argnames":
            out |= {v for v in (const_str(e) for e in vals) if v}
        elif kw.arg == "static_argnums":
            for e in vals:
                if isinstance(e, ast.Constant) and isinstance(e.value, int) \
                        and 0 <= e.value < len(pos):
                    out.add(pos[e.value])
    return out


def traced_functions(
    f: FileInfo, project: Project
) -> List[Tuple[ast.AST, Set[str]]]:
    """``(fn, static_param_names)`` for every FunctionDef/Lambda staged
    for tracing in this file: bodies decorated with a jit wrapper,
    passed to a jit call, or passed to a lax control-flow/shard_map
    combinator (matched by name — a local ``def body(...)`` referenced
    as ``lax.while_loop(cond, body, ...)`` is resolved through the
    file's def table)."""
    key = f"traced::{f.rel}"
    cached = project.cache.get(key)
    if cached is not None:
        return cached  # type: ignore[return-value]
    defs_by_name: Dict[str, List[ast.AST]] = {}
    for node in ast.walk(f.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs_by_name.setdefault(node.name, []).append(node)
    traced: List[Tuple[ast.AST, Set[str]]] = []
    seen: Dict[int, int] = {}

    def mark(fn: ast.AST, static: Set[str]) -> None:
        i = seen.get(id(fn))
        if i is None:
            seen[id(fn)] = len(traced)
            traced.append((fn, static))
        else:
            traced[i] = (fn, traced[i][1] | static)

    for node in ast.walk(f.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for d in node.decorator_list:
                if _is_trace_decorator(d):
                    mark(node, _static_params(d, node))
        if not isinstance(node, ast.Call):
            continue
        t = terminal_name(node.func)
        if t not in _TRACE_WRAPPERS and t not in _TRACE_HOFS:
            continue
        static_names: Set[str] = set()
        for kw in node.keywords:
            if kw.arg == "static_argnames":
                vals = (
                    kw.value.elts
                    if isinstance(kw.value, (ast.Tuple, ast.List))
                    else [kw.value]
                )
                static_names |= {
                    v for v in (const_str(e) for e in vals) if v
                }
        for arg in node.args:
            if isinstance(arg, ast.Lambda):
                mark(arg, set())
            elif isinstance(arg, ast.Name):
                for fn in defs_by_name.get(arg.id, ()):
                    mark(fn, set(static_names))
    project.cache[key] = traced
    return traced


def _param_names(fn: ast.AST) -> Set[str]:
    a = fn.args
    names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return set(names)


def _bare_param_use(node: ast.AST, params: Set[str]) -> Optional[ast.Name]:
    """A Name in ``node``'s subtree that references a traced parameter
    *as a value* — uses under an attribute access (``A.shape``,
    ``x.dtype``: static under tracing), as the operand of ``len()`` /
    ``isinstance()``, or inside identity (``is``/``is not``) compares
    are exempt."""
    for sub in ast.walk(node):
        if not (isinstance(sub, ast.Name) and sub.id in params):
            continue
        parent = getattr(sub, "slate_parent", None)
        if isinstance(parent, ast.Attribute) and parent.value is sub:
            continue  # A.shape / A.ndim / A.dtype are static
        if isinstance(parent, ast.Call) and terminal_name(parent.func) in (
            "len", "isinstance", "id", "type",
        ):
            continue
        skip = False
        for anc in parents(sub):
            if isinstance(anc, ast.Compare) and all(
                isinstance(op, (ast.Is, ast.IsNot)) for op in anc.ops
            ):
                skip = True  # identity checks never read the value
                break
            if anc is node:
                break
        if skip:
            continue
        return sub
    return None


@rule
class TraceSafety(Rule):
    """Inside functions staged for tracing, flag host control flow on
    traced parameters, scalar coercions of them, and ``np.*`` calls
    over them."""

    name = "trace-safety"
    summary = (
        "no Python if/while, bool()/int()/float(), or np.* on traced "
        "values inside jit/while_loop/cond/scan/shard_map bodies"
    )
    bug = "traced-value misuse (shard_map collection kill, trace crashes)"

    def check_file(self, f: FileInfo, project: Project):
        for fn, static in traced_functions(f, project):
            params = _param_names(fn) - static
            if not params:
                continue
            body = fn.body if isinstance(fn.body, list) else [fn.body]
            for stmt in body:
                for node in ast.walk(stmt):
                    if isinstance(node, (ast.If, ast.While)):
                        use = _bare_param_use(node.test, params)
                        if use is not None:
                            kind = (
                                "if" if isinstance(node, ast.If) else "while"
                            )
                            yield Finding(
                                self.name, f.rel, node.lineno,
                                node.col_offset,
                                f"Python `{kind}` on traced value "
                                f"{use.id!r} inside a staged function — "
                                "use lax.cond/lax.while_loop (or hoist "
                                "the decision out of the traced body)",
                            )
                    elif isinstance(node, ast.Call):
                        t = terminal_name(node.func)
                        if (
                            isinstance(node.func, ast.Name)
                            and t in ("bool", "int", "float")
                            and node.args
                            and isinstance(node.args[0], ast.Name)
                            and node.args[0].id in params
                        ):
                            yield Finding(
                                self.name, f.rel, node.lineno,
                                node.col_offset,
                                f"{t}() coerces traced value "
                                f"{node.args[0].id!r} to a host scalar "
                                "inside a staged function",
                            )
                        elif (
                            root_name(node.func) in _NP_ROOTS
                            and isinstance(node.func, ast.Attribute)
                        ):
                            use = None
                            for arg in node.args:
                                use = _bare_param_use(arg, params)
                                if use is not None:
                                    break
                            if use is not None:
                                yield Finding(
                                    self.name, f.rel, node.lineno,
                                    node.col_offset,
                                    f"host numpy call on traced value "
                                    f"{use.id!r} inside a staged "
                                    "function — use jnp/lax",
                                )


# ---------------------------------------------------------------------------
# pytree safety
# ---------------------------------------------------------------------------


def enum_class_names(project: Project) -> Set[str]:
    """Names of classes inheriting an Enum variant anywhere in the
    linted tree (``Option``, ``Schedule``, ... from enums.py)."""
    cached = project.cache.get("enum_classes")
    if cached is not None:
        return cached  # type: ignore[return-value]
    out: Set[str] = set()
    for f in project.files:
        for node in ast.walk(f.tree):
            if isinstance(node, ast.ClassDef) and any(
                "Enum" in (terminal_name(b) or "") for b in node.bases
            ):
                out.add(node.name)
    project.cache["enum_classes"] = out
    return out


_JAX_ROOTS = {"jax", "jnp", "lax"}


def _reaches_jax(node: ast.AST) -> bool:
    """The dict literal is an argument of a jax-ish call (jit'd
    dispatch, lax combinator, tree op)."""
    parent = getattr(node, "slate_parent", None)
    while isinstance(parent, (ast.keyword, ast.Starred)):
        parent = getattr(parent, "slate_parent", None)
    if not isinstance(parent, ast.Call):
        return False
    func = parent.func
    while isinstance(func, ast.Call):
        func = func.func  # jax.jit(f)({...}) — unwrap to the jit call
    t = terminal_name(func)
    return (
        root_name(func) in _JAX_ROOTS
        or t in _TRACE_WRAPPERS
        or t in _TRACE_HOFS
    )


@rule
class PytreeSafety(Rule):
    """Enum-keyed dict literals reaching jax, and array-carrying
    dataclasses whose generated ``__eq__`` compares ndarrays."""

    name = "pytree-safety"
    summary = (
        "no enum-keyed dicts into jax APIs; @dataclass with "
        "ndarray/Array fields needs eq=False"
    )
    bug = "unorderable-enum pytree crash; ndarray-__eq__ dataclass"

    def check_file(self, f: FileInfo, project: Project):
        enums = enum_class_names(project)
        traced = traced_functions(f, project)
        traced_ids = {id(t) for t, _static in traced}
        if enums:
            for node in ast.walk(f.tree):
                if not isinstance(node, ast.Dict):
                    continue
                key = next(
                    (
                        k for k in node.keys
                        if isinstance(k, ast.Attribute)
                        and isinstance(k.value, ast.Name)
                        and k.value.id in enums
                    ),
                    None,
                )
                if key is None:
                    continue
                in_traced = any(
                    id(anc) in traced_ids for anc in parents(node)
                )
                if in_traced or _reaches_jax(node):
                    yield Finding(
                        self.name, f.rel, node.lineno, node.col_offset,
                        f"dict keyed by enum member "
                        f"{ast.unparse(key)} reaches a jax API — pytree "
                        "flattening sorts dict keys and enums are "
                        "unorderable; key by .value (or pass the dict "
                        "outside the traced boundary)",
                    )
        yield from self._check_dataclasses(f)

    def _check_dataclasses(self, f: FileInfo):
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            dc = None
            pytree_registered = False
            eq_false = False
            for dec in node.decorator_list:
                t = terminal_name(dec if not isinstance(dec, ast.Call)
                                  else dec.func)
                if t == "dataclass":
                    dc = dec
                    if isinstance(dec, ast.Call):
                        eq_false = any(
                            kw.arg == "eq"
                            and isinstance(kw.value, ast.Constant)
                            and kw.value.value is False
                            for kw in dec.keywords
                        )
                elif t == "register_pytree_node_class":
                    pytree_registered = True
            if dc is None or eq_false or pytree_registered:
                # pytree-registered classes define their own flatten
                # contract and are never compared as dataclasses
                continue
            for stmt in node.body:
                if not isinstance(stmt, ast.AnnAssign):
                    continue
                ann = ast.unparse(stmt.annotation)
                if "ndarray" in ann or "Array" in ann:
                    yield Finding(
                        self.name, f.rel, node.lineno, node.col_offset,
                        f"@dataclass {node.name} has array-typed field "
                        f"{ast.unparse(stmt.target)!r} ({ann}) but no "
                        "eq=False — the generated __eq__ compares "
                        "ndarrays (truthiness raises; equal-content "
                        "instances alias in remove()-based sweeps)",
                    )
                    break
