"""slate-lint: AST-based invariant checking for the contracts every
review pass has been policing by hand.

Ten rules, each mechanizing a recurring bug class from CHANGES.md
(see each rule's ``bug`` attribute and the README "Static analysis"
section):

======================  =====================================================
rule                    invariant
======================  =====================================================
``metric-drift``        report-joined / README-listed metric names have
                        emitters under slate_tpu/
``fault-site``          chaos call sites are declared in the aux/faults.py
                        SITE_SPECS registry with real recovery counters
``hot-path-gating``     serve-path observability calls with costly
                        arguments sit behind the armed-flag gate
``trace-safety``        no host control flow / coercions / np.* on traced
                        values inside staged functions
``pytree-safety``       no enum-keyed dicts into jax; array dataclasses
                        carry eq=False
``lock-discipline``     ``# guarded by: <lock>`` fields only touched under
                        the lock (intraprocedural, per file)
``race-guarded-by``     whole-program lock discipline: ``*_locked``
                        helpers called with their locks held, resolvable
                        annotated fields checked across modules
``race-lock-order``     the nested-lock acquisition graph over
                        serve/+integrity/+aux/ is acyclic; new edges vs
                        the checked-in LOCK_ORDER.json are findings
``env-drift``           SLATE_TPU_* knobs and README env tables agree
``exception-context``   serve-path SlateError raises attach with_context()
======================  =====================================================

Usage::

    from slate_tpu import analysis
    result = analysis.run("/path/to/repo")
    print(result.render());  assert result.ok

or from the CLI / CI gate: ``python tools/slate_lint.py`` and
``python run_tests.py --lint``.  Suppress a deliberate violation with
``# slate-lint: disable=<rule>`` on the flagged line; accept legacy
findings via the checked-in ``.slate-lint-baseline.json``
(``tools/slate_lint.py --write-baseline``).  The framework is
stdlib-only and never imports the code it checks.
"""

from .core import (  # noqa: F401
    BASELINE_NAME,
    Finding,
    LintResult,
    RULES,
    Rule,
    load_baseline,
    run,
    write_baseline,
)

# importing the rule modules populates the registry
from . import rules_metrics  # noqa: F401,E402
from . import rules_faults  # noqa: F401,E402
from . import rules_trace  # noqa: F401,E402
from . import rules_concurrency  # noqa: F401,E402
from . import rules_env  # noqa: F401,E402
from . import races  # noqa: F401,E402
from .races import LOCK_GRAPH_NAME  # noqa: F401,E402

__all__ = [
    "BASELINE_NAME", "Finding", "LintResult", "RULES", "Rule",
    "load_baseline", "run", "write_baseline",
]
