#!/usr/bin/env python
"""Sweep runner (reference: test/run_tests.py — builds command lists per
routine class with size presets quick/small/medium, JUnit XML output).

Usage:
    python run_tests.py                     # quick preset, all routines
    python run_tests.py --size small --grid 2x2 --xml results.xml gemm posv
    python run_tests.py --target d          # accepted for reference parity
"""

import argparse
import os
import sys

PRESETS = {
    "quick": {"dim": "32,50", "nb": "16", "type": "d"},
    "small": {"dim": "64,100", "nb": "16,32", "type": "s,d"},
    "medium": {"dim": "128,256", "nb": "32,64", "type": "s,d,c,z"},
}


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("routines", nargs="*", default=[])
    ap.add_argument("--size", default="quick", choices=sorted(PRESETS))
    ap.add_argument("--grid", default="1x1")
    ap.add_argument("--xml", default=None)
    ap.add_argument("--target", default="d")
    ap.add_argument("--type", default=None)
    args = ap.parse_args()

    # virtual devices for multi-process grids (tests force the cpu
    # platform; the TPU plugin ignores JAX_PLATFORMS so set via config)
    p, q = (int(x) for x in args.grid.split("x"))
    if p * q > 1:
        os.environ.setdefault(
            "XLA_FLAGS",
            f"--xla_force_host_platform_device_count={max(8, p * q)}",
        )
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax

    jax.config.update("jax_enable_x64", True)

    from slate_tpu.testing.tester import run

    preset = PRESETS[args.size]
    argv = list(args.routines) if args.routines else ["all"]
    argv += ["--dim", preset["dim"], "--nb", preset["nb"]]
    argv += ["--type", args.type or preset["type"]]
    argv += ["--grid", args.grid, "--target", args.target]
    if args.xml:
        argv += ["--xml", args.xml]
    return run(argv)


if __name__ == "__main__":
    sys.exit(main())
