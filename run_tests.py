#!/usr/bin/env python
"""Sweep runner (reference: test/run_tests.py — builds command lists per
routine class with size presets quick/small/medium, JUnit XML output).

Usage:
    python run_tests.py                     # quick preset, all routines
    python run_tests.py --size small --grid 2x2 --xml results.xml gemm posv
    python run_tests.py --target d          # accepted for reference parity
"""

import argparse
import os
import re
import subprocess
import sys
import threading
import time

PRESETS = {
    "quick": {"dim": "32,50", "nb": "16", "type": "d"},
    "small": {"dim": "64,100", "nb": "16,32", "type": "s,d"},
    "medium": {"dim": "128,256", "nb": "32,64", "type": "s,d,c,z"},
}


# The ROADMAP tier-1 contract, verbatim: command shape, 870 s timeout
# (kill 10 s after terminate), and DOTS_PASSED accounting over the
# progress lines.  `python run_tests.py --tier1` replaces hand-pasting.
TIER1_TIMEOUT = 870.0
TIER1_KILL_GRACE = 10.0
_DOTS_RE = re.compile(r"^[.FEsx]+( *\[ *[0-9]+%\])?$")


def tier1() -> int:
    cmd = [
        sys.executable, "-m", "pytest", "tests/", "-q", "-m", "not slow",
        "--continue-on-collection-errors", "-p", "no:cacheprovider",
        "-p", "no:xdist", "-p", "no:randomly",
    ]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    t0 = time.monotonic()
    proc = subprocess.Popen(
        cmd, env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, cwd=os.path.dirname(os.path.abspath(__file__)) or ".",
    )
    timed_out = False

    def _watchdog():
        nonlocal timed_out
        try:
            proc.wait(timeout=TIER1_TIMEOUT)
        except subprocess.TimeoutExpired:
            timed_out = True
            proc.terminate()
            try:
                proc.wait(timeout=TIER1_KILL_GRACE)
            except subprocess.TimeoutExpired:
                proc.kill()

    w = threading.Thread(target=_watchdog, daemon=True)
    w.start()
    dots = 0
    assert proc.stdout is not None
    for line in proc.stdout:
        sys.stdout.write(line)
        sys.stdout.flush()
        if _DOTS_RE.match(line.rstrip("\n")):
            dots += line.count(".")
    rc = proc.wait()
    w.join()
    if timed_out:
        rc = 124  # the driver's `timeout` convention
    print(f"DOTS_PASSED={dots}")
    print(f"tier1: rc={rc} wall={time.monotonic() - t0:.0f}s")
    return rc


def schedules_smoke() -> int:
    """Parity gate for the factorization schedules: the whole of
    tests/test_recursive_schedules.py across all four dtypes
    (marker-independent — the slow marks only budget the tier-1 gate),
    including the cheap n=256 driver-routing/metrics tests, minus only
    the heavy n=2048 end-to-end driver case.  For touching
    ops/*_kernels.py or the drivers' Option.Schedule routing without
    paying a full tier-1."""
    cmd = [
        sys.executable, "-m", "pytest",
        "tests/test_recursive_schedules.py", "-q",
        "-k", "not driver_n2048",
        "-p", "no:cacheprovider", "-p", "no:xdist", "-p", "no:randomly",
    ]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.call(
        cmd, env=env,
        cwd=os.path.dirname(os.path.abspath(__file__)) or ".",
    )


# Env-activated mixed-precision stream for the --refine gate: metrics
# are read at import (the production activation path); the atexit dump
# writes the JSONL refine_report joins.  One deliberately ill-
# conditioned system exercises the fallback, well under the report's
# rate threshold.
_REFINE_DRIVER = """
import jax
jax.config.update("jax_enable_x64", True)  # the f64/f32 pair is the gate
import numpy as np
import slate_tpu as st
from slate_tpu.matgen import cond_matrix
from slate_tpu.matrix.matrix import HermitianMatrix, Matrix

B = np.arange(96, dtype=np.float64).reshape(48, 2) / 48.0
for seed in (0, 1, 2):
    A = cond_matrix(48, 1e3, seed=seed)
    X, info, iters = st.gesv_mixed(Matrix.from_global(A, 16),
                                   Matrix.from_global(B, 16))
    assert int(info) == 0 and iters >= 0, (int(info), iters)
S = cond_matrix(48, 1e4, spd=True)
X, info, iters = st.posv_mixed(
    HermitianMatrix.from_global(S, 16, uplo=st.Uplo.Lower),
    Matrix.from_global(B, 16))
assert int(info) == 0 and iters >= 0
# divergence leg: cond >> 1/eps_f32 must demote to the fallback solver
A = cond_matrix(48, 1e9)
X, info, iters = st.gesv_mixed(Matrix.from_global(A, 16),
                               Matrix.from_global(B, 16))
assert int(info) == 0 and iters < 0, (int(info), iters)
assert np.all(np.isfinite(np.asarray(X.to_global())))
print("refine driver: 4 converged, 1 fallback, 0 hangs")
"""


def refine_gate() -> int:
    """Refine gate, two legs: (1) the mixed-precision suite (slow
    parametrizations included); (2) an env-activated driver stream
    (SLATE_TPU_METRICS, the production path) whose JSONL is joined by
    tools/refine_report.py — a fallback rate past the threshold fails
    the gate."""
    import tempfile

    here = os.path.dirname(os.path.abspath(__file__)) or "."
    cmd = [
        sys.executable, "-m", "pytest", "tests/test_refine.py", "-q",
        "-p", "no:cacheprovider", "-p", "no:xdist", "-p", "no:randomly",
    ]
    rc = subprocess.call(cmd, env=dict(os.environ, JAX_PLATFORMS="cpu"),
                         cwd=here)
    if rc != 0:
        return rc
    jsonl = os.path.join(tempfile.gettempdir(), f"refine_{os.getpid()}.jsonl")
    env = dict(os.environ, JAX_PLATFORMS="cpu", SLATE_TPU_METRICS=jsonl)
    try:
        rc = subprocess.call([sys.executable, "-c", _REFINE_DRIVER], env=env,
                             cwd=here)
        if rc != 0:
            return rc
        return subprocess.call(
            [sys.executable, os.path.join("tools", "refine_report.py"),
             jsonl, "--max-fallback-rate", "0.5"],
            cwd=here,
        )
    finally:
        try:
            os.unlink(jsonl)
        except OSError:
            pass


# Env-activated faulty stream for the --chaos gate: SLATE_TPU_FAULTS +
# SLATE_TPU_METRICS are read at import (the production activation path),
# the atexit dump writes the JSONL chaos_report joins.
_CHAOS_DRIVER = """
import numpy as np
from slate_tpu.exceptions import SlateError
from slate_tpu.serve.cache import ExecutableCache
from slate_tpu.serve.service import SolverService

rng = np.random.default_rng(0)
n = 12
svc = SolverService(cache=ExecutableCache(manifest_path=None), batch_max=4,
                    dim_floor=16, nrhs_floor=4, retry_backoff_s=0.002,
                    breaker_cooldown_s=0.02, retry_seed=0)
futs = [svc.submit("gesv", rng.standard_normal((n, n)) + n * np.eye(n),
                   rng.standard_normal((n, 2)), retries=2)
        for _ in range(24)]
ok = typed = 0
for f in futs:
    try:
        assert np.all(np.isfinite(f.result(timeout=300)))
        ok += 1
    except SlateError:
        typed += 1
assert ok + typed == len(futs), "a future hung"
print(f"chaos driver: {ok} solved, {typed} typed errors, 0 hangs")
svc.stop()
"""


def chaos() -> int:
    """Chaos gate, two legs: (1) the fault-injection suite — every
    site x hardening combination including the slow-marked sustained
    streams; (2) an env-activated faulty stream (SLATE_TPU_FAULTS +
    SLATE_TPU_METRICS, the production path) whose JSONL is joined by
    tools/chaos_report.py — a fault site with injections but no
    recovery signal fails the gate."""
    import tempfile

    here = os.path.dirname(os.path.abspath(__file__)) or "."
    cmd = [
        sys.executable, "-m", "pytest", "tests/test_chaos.py", "-q",
        "-p", "no:cacheprovider", "-p", "no:xdist", "-p", "no:randomly",
    ]
    rc = subprocess.call(cmd, env=dict(os.environ, JAX_PLATFORMS="cpu"),
                         cwd=here)
    if rc != 0:
        return rc
    jsonl = os.path.join(tempfile.gettempdir(), f"chaos_{os.getpid()}.jsonl")
    env = dict(
        os.environ, JAX_PLATFORMS="cpu", SLATE_TPU_METRICS=jsonl,
        SLATE_TPU_FAULTS="execute:p=0.3,seed=3;worker_death:every=7",
    )
    try:
        rc = subprocess.call([sys.executable, "-c", _CHAOS_DRIVER], env=env,
                             cwd=here)
        if rc != 0:
            return rc
        return subprocess.call(
            [sys.executable, os.path.join("tools", "chaos_report.py"), jsonl],
            cwd=here,
        )
    finally:
        try:
            os.unlink(jsonl)
        except OSError:
            pass


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--tier1", action="store_true",
                    help="run the exact ROADMAP tier-1 gate (870 s timeout, "
                         "DOTS_PASSED accounting) and exit")
    ap.add_argument("--schedules", action="store_true",
                    help="run the factorization-schedule parity smoke "
                         "(recursive vs flat vs scipy) and exit")
    ap.add_argument("--chaos", action="store_true",
                    help="run the fault-injection suite (slow matrix "
                         "included) + the chaos_report recovery gate")
    ap.add_argument("--refine", action="store_true",
                    help="run the mixed-precision refinement suite + the "
                         "refine_report fallback-rate gate")
    ap.add_argument("routines", nargs="*", default=[])
    ap.add_argument("--size", default="quick", choices=sorted(PRESETS))
    ap.add_argument("--grid", default="1x1")
    ap.add_argument("--xml", default=None)
    ap.add_argument("--target", default="d")
    ap.add_argument("--type", default=None)
    args = ap.parse_args()

    if args.tier1:
        return tier1()
    if args.schedules:
        return schedules_smoke()
    if args.chaos:
        return chaos()
    if args.refine:
        return refine_gate()

    # virtual devices for multi-process grids (tests force the cpu
    # platform; the TPU plugin ignores JAX_PLATFORMS so set via config)
    p, q = (int(x) for x in args.grid.split("x"))
    if p * q > 1:
        os.environ.setdefault(
            "XLA_FLAGS",
            f"--xla_force_host_platform_device_count={max(8, p * q)}",
        )
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax

    jax.config.update("jax_enable_x64", True)

    from slate_tpu.testing.tester import run

    preset = PRESETS[args.size]
    argv = list(args.routines) if args.routines else ["all"]
    argv += ["--dim", preset["dim"], "--nb", preset["nb"]]
    argv += ["--type", args.type or preset["type"]]
    argv += ["--grid", args.grid, "--target", args.target]
    if args.xml:
        argv += ["--xml", args.xml]
    return run(argv)


if __name__ == "__main__":
    sys.exit(main())
