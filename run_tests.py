#!/usr/bin/env python
"""Sweep runner (reference: test/run_tests.py — builds command lists per
routine class with size presets quick/small/medium, JUnit XML output).

Usage:
    python run_tests.py                     # quick preset, all routines
    python run_tests.py --size small --grid 2x2 --xml results.xml gemm posv
    python run_tests.py --target d          # accepted for reference parity
"""

import argparse
import os
import re
import subprocess
import sys
import threading
import time

PRESETS = {
    "quick": {"dim": "32,50", "nb": "16", "type": "d"},
    "small": {"dim": "64,100", "nb": "16,32", "type": "s,d"},
    "medium": {"dim": "128,256", "nb": "32,64", "type": "s,d,c,z"},
}


# The ROADMAP tier-1 contract, verbatim: command shape, 870 s timeout
# (kill 10 s after terminate), and DOTS_PASSED accounting over the
# progress lines.  `python run_tests.py --tier1` replaces hand-pasting.
TIER1_TIMEOUT = 870.0
TIER1_KILL_GRACE = 10.0
_DOTS_RE = re.compile(r"^[.FEsx]+( *\[ *[0-9]+%\])?$")


def tier1() -> int:
    cmd = [
        sys.executable, "-m", "pytest", "tests/", "-q", "-m", "not slow",
        "--continue-on-collection-errors", "-p", "no:cacheprovider",
        "-p", "no:xdist", "-p", "no:randomly",
    ]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    t0 = time.monotonic()
    proc = subprocess.Popen(
        cmd, env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, cwd=os.path.dirname(os.path.abspath(__file__)) or ".",
    )
    timed_out = False

    def _watchdog():
        nonlocal timed_out
        try:
            proc.wait(timeout=TIER1_TIMEOUT)
        except subprocess.TimeoutExpired:
            timed_out = True
            proc.terminate()
            try:
                proc.wait(timeout=TIER1_KILL_GRACE)
            except subprocess.TimeoutExpired:
                proc.kill()

    w = threading.Thread(target=_watchdog, daemon=True)
    w.start()
    dots = 0
    assert proc.stdout is not None
    for line in proc.stdout:
        sys.stdout.write(line)
        sys.stdout.flush()
        if _DOTS_RE.match(line.rstrip("\n")):
            dots += line.count(".")
    rc = proc.wait()
    w.join()
    if timed_out:
        rc = 124  # the driver's `timeout` convention
    print(f"DOTS_PASSED={dots}")
    print(f"tier1: rc={rc} wall={time.monotonic() - t0:.0f}s")
    return rc


def schedules_smoke() -> int:
    """Parity gate for the factorization schedules: the whole of
    tests/test_recursive_schedules.py across all four dtypes
    (marker-independent — the slow marks only budget the tier-1 gate),
    including the cheap n=256 driver-routing/metrics tests, minus only
    the heavy n=2048 end-to-end driver case.  For touching
    ops/*_kernels.py or the drivers' Option.Schedule routing without
    paying a full tier-1."""
    cmd = [
        sys.executable, "-m", "pytest",
        "tests/test_recursive_schedules.py", "-q",
        "-k", "not driver_n2048",
        "-p", "no:cacheprovider", "-p", "no:xdist", "-p", "no:randomly",
    ]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.call(
        cmd, env=env,
        cwd=os.path.dirname(os.path.abspath(__file__)) or ".",
    )


# Env-activated mixed-precision stream for the --refine gate: metrics
# are read at import (the production activation path); the atexit dump
# writes the JSONL refine_report joins.  One deliberately ill-
# conditioned system exercises the fallback, well under the report's
# rate threshold.
_REFINE_DRIVER = """
import jax
jax.config.update("jax_enable_x64", True)  # the f64/f32 pair is the gate
import numpy as np
import slate_tpu as st
from slate_tpu.matgen import cond_matrix
from slate_tpu.matrix.matrix import HermitianMatrix, Matrix

B = np.arange(96, dtype=np.float64).reshape(48, 2) / 48.0
for seed in (0, 1, 2):
    A = cond_matrix(48, 1e3, seed=seed)
    X, info, iters = st.gesv_mixed(Matrix.from_global(A, 16),
                                   Matrix.from_global(B, 16))
    assert int(info) == 0 and iters >= 0, (int(info), iters)
S = cond_matrix(48, 1e4, spd=True)
X, info, iters = st.posv_mixed(
    HermitianMatrix.from_global(S, 16, uplo=st.Uplo.Lower),
    Matrix.from_global(B, 16))
assert int(info) == 0 and iters >= 0
# divergence leg: cond >> 1/eps_f32 must demote to the fallback solver
A = cond_matrix(48, 1e9)
X, info, iters = st.gesv_mixed(Matrix.from_global(A, 16),
                               Matrix.from_global(B, 16))
assert int(info) == 0 and iters < 0, (int(info), iters)
assert np.all(np.isfinite(np.asarray(X.to_global())))
print("refine driver: 4 converged, 1 fallback, 0 hangs")
"""


def refine_gate() -> int:
    """Refine gate, two legs: (1) the mixed-precision suite (slow
    parametrizations included); (2) an env-activated driver stream
    (SLATE_TPU_METRICS, the production path) whose JSONL is joined by
    tools/refine_report.py — a fallback rate past the threshold fails
    the gate."""
    import tempfile

    here = os.path.dirname(os.path.abspath(__file__)) or "."
    cmd = [
        sys.executable, "-m", "pytest", "tests/test_refine.py", "-q",
        "-p", "no:cacheprovider", "-p", "no:xdist", "-p", "no:randomly",
    ]
    rc = subprocess.call(cmd, env=dict(os.environ, JAX_PLATFORMS="cpu"),
                         cwd=here)
    if rc != 0:
        return rc
    jsonl = os.path.join(tempfile.gettempdir(), f"refine_{os.getpid()}.jsonl")
    env = dict(os.environ, JAX_PLATFORMS="cpu", SLATE_TPU_METRICS=jsonl)
    try:
        rc = subprocess.call([sys.executable, "-c", _REFINE_DRIVER], env=env,
                             cwd=here)
        if rc != 0:
            return rc
        return subprocess.call(
            [sys.executable, os.path.join("tools", "refine_report.py"),
             jsonl, "--max-fallback-rate", "0.5"],
            cwd=here,
        )
    finally:
        try:
            os.unlink(jsonl)
        except OSError:
            pass


# Env-activated faulty stream for the --chaos gate: SLATE_TPU_FAULTS +
# SLATE_TPU_METRICS are read at import (the production activation path),
# the atexit dump writes the JSONL chaos_report joins.
_CHAOS_DRIVER = """
import numpy as np
from slate_tpu.exceptions import SlateError
from slate_tpu.serve.cache import ExecutableCache
from slate_tpu.serve.service import SolverService

rng = np.random.default_rng(0)
n = 12
svc = SolverService(cache=ExecutableCache(manifest_path=None), batch_max=4,
                    dim_floor=16, nrhs_floor=4, retry_backoff_s=0.002,
                    breaker_cooldown_s=0.02, retry_seed=0)
futs = [svc.submit("gesv", rng.standard_normal((n, n)) + n * np.eye(n),
                   rng.standard_normal((n, 2)), retries=2)
        for _ in range(24)]
ok = typed = 0
for f in futs:
    try:
        assert np.all(np.isfinite(f.result(timeout=300)))
        ok += 1
    except SlateError:
        typed += 1
assert ok + typed == len(futs), "a future hung"
print(f"chaos driver: {ok} solved, {typed} typed errors, 0 hangs")
svc.stop()
"""

# Artifact leg of the --chaos gate: SLATE_TPU_FAULTS arms the three
# artifact sites (env path, read at import), a store is warmed (misses
# never advance the fault sites — the ladder starts after a successful
# read), then four loads eat one injection each and the fourth proves
# the store healthy.  chaos_report joins faults.injected.artifact_*
# against the detection counters.
_CHAOS_ARTIFACT_DRIVER = """
import os
import tempfile
import jax
jax.config.update("jax_enable_x64", True)  # the production f64/x64 config
import numpy as np
from slate_tpu.serve import buckets as bk
from slate_tpu.serve.cache import ExecutableCache

td = tempfile.mkdtemp(prefix="slate_chaos_art_")
cache = ExecutableCache(manifest_path=os.path.join(td, "m.json"),
                        artifact_dir=os.path.join(td, "a"))
key = bk.bucket_for("gesv", 10, 10, 2, np.float64, floor=16,
                    nrhs_floor=4, schedule="recursive")
cache.ensure_manifest(key, (1,))
cache.warmup(batch_max=1)  # builds + persists the export artifact
st = cache.artifacts
outcomes = []
for i in range(4):  # corrupt, stale, load_fail fire once each, then clean
    outcomes.append(st.load(key, 1) is not None)
assert outcomes == [False, False, False, True], outcomes
print("chaos artifact driver: 3 injected loads degraded, 4th verified clean")
"""


def chaos() -> int:
    """Chaos gate, three legs: (1) the fault-injection suite — every
    site x hardening combination including the slow-marked sustained
    streams; (2) an env-activated faulty stream (SLATE_TPU_FAULTS +
    SLATE_TPU_METRICS, the production path) whose JSONL is joined by
    tools/chaos_report.py — a fault site with injections but no
    recovery signal fails the gate; (3) the same join over the three
    artifact-store load sites (artifact_corrupt/_stale/_load_fail),
    run as its own pass so the per-site attribution is airtight."""
    import tempfile

    here = os.path.dirname(os.path.abspath(__file__)) or "."
    cmd = [
        sys.executable, "-m", "pytest", "tests/test_chaos.py", "-q",
        "-p", "no:cacheprovider", "-p", "no:xdist", "-p", "no:randomly",
    ]
    rc = subprocess.call(cmd, env=dict(os.environ, JAX_PLATFORMS="cpu"),
                         cwd=here)
    if rc != 0:
        return rc
    legs = (
        (_CHAOS_DRIVER, "execute:p=0.3,seed=3;worker_death:every=7"),
        (_CHAOS_ARTIFACT_DRIVER,
         "artifact_corrupt:once;artifact_stale:once;"
         "artifact_load_fail:once"),
    )
    for i, (driver, faults_spec) in enumerate(legs):
        jsonl = os.path.join(
            tempfile.gettempdir(), f"chaos_{os.getpid()}_{i}.jsonl"
        )
        env = dict(
            os.environ, JAX_PLATFORMS="cpu", SLATE_TPU_METRICS=jsonl,
            SLATE_TPU_FAULTS=faults_spec,
        )
        try:
            rc = subprocess.call(
                [sys.executable, "-c", driver], env=env, cwd=here
            )
            if rc == 0:
                rc = subprocess.call(
                    [sys.executable,
                     os.path.join("tools", "chaos_report.py"), jsonl],
                    cwd=here,
                )
            if rc != 0:
                return rc
        finally:
            try:
                os.unlink(jsonl)
            except OSError:
                pass
    return 0


# Env-activated placement stream for the --sharded gate: a forced
# 8-fake-device CPU mesh (XLA_FLAGS in the gate env, set before jax
# imports), a replica-pool service with an spmd submesh, a warmed mixed
# small/large stream that must stay compile-free, and an atexit metrics
# dump tools/placement_report.py joins (nonzero on a starved replica).
_SHARDED_DRIVER = """
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
import numpy as np
from slate_tpu.aux import metrics
from slate_tpu.serve import buckets as bk
from slate_tpu.serve.cache import ExecutableCache
from slate_tpu.serve.placement import PlacementPolicy
from slate_tpu.serve.service import SolverService

assert len(jax.devices()) >= 8, jax.devices()
rng = np.random.default_rng(0)
svc = SolverService(
    cache=ExecutableCache(manifest_path=None), batch_max=4,
    batch_window_s=0.002, dim_floor=16, nrhs_floor=4,
    placement=PlacementPolicy(replicas=3, mesh="2x2", shard_threshold=40),
)
key_s = bk.bucket_for("gesv", 12, 12, 2, np.float64, floor=16, nrhs_floor=4)
key_l = bk.bucket_for("gesv", 50, 50, 2, np.float64, floor=16, nrhs_floor=4,
                      mesh="2x2")
svc.cache.ensure_manifest(key_s, (1, 4))
svc.cache.ensure_manifest(key_l, (1,))
svc.warmup()  # primes all 3 replica devices + the spmd executable

def prob(n, seed):
    r = np.random.default_rng(seed)
    return (r.standard_normal((n, n)) + n * np.eye(n),
            r.standard_normal((n, 2)))

probs = [prob(12, i) for i in range(18)] + [prob(50, 100 + i)
                                            for i in range(2)]
with metrics.deltas() as d:
    futs = [svc.submit("gesv", A, B) for A, B in probs]
    for (A, B), f in zip(probs, futs):
        X = f.result(timeout=600)
        assert np.abs(X - np.linalg.solve(A, B)).max() < 1e-8
    assert d.get("jit.compilations") == 0, (
        "warmed placement stream compiled: %d" % d.get("jit.compilations"))
    assert d.get("serve.routed_sharded") == 2
    assert d.get("serve.replicated_dispatch") == 18
busy = [r["name"] for r in svc.health()["replicas"] if r["dispatched"] > 0]
assert len(busy) >= 2, busy
print(f"sharded driver: 18 replicated over replicas {busy}, "
      "2 sharded on 2x2, 0 steady-state compiles")
svc.stop()
"""


def sharded() -> int:
    """Sharded-serving gate, two legs: (1) the placement suite
    (policy units + the 8-fake-device acceptance stream); (2) an
    env-activated placement stream (SLATE_TPU_METRICS, forced device
    count — the production activation path) whose JSONL is joined by
    tools/placement_report.py — a starved replica fails the gate."""
    import tempfile

    here = os.path.dirname(os.path.abspath(__file__)) or "."
    rc = subprocess.call(
        [sys.executable, "-m", "pytest", "tests/test_placement.py", "-q",
         "-p", "no:cacheprovider", "-p", "no:xdist", "-p", "no:randomly"],
        env=dict(os.environ, JAX_PLATFORMS="cpu"), cwd=here,
    )
    if rc != 0:
        return rc
    jsonl = os.path.join(
        tempfile.gettempdir(), f"placement_{os.getpid()}.jsonl"
    )
    env = dict(
        os.environ, JAX_PLATFORMS="cpu", SLATE_TPU_METRICS=jsonl,
        XLA_FLAGS=(
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        ).strip(),
    )
    try:
        rc = subprocess.call(
            [sys.executable, "-c", _SHARDED_DRIVER], env=env, cwd=here
        )
        if rc != 0:
            return rc
        return subprocess.call(
            [sys.executable, os.path.join("tools", "placement_report.py"),
             jsonl],
            cwd=here,
        )
    finally:
        try:
            os.unlink(jsonl)
        except OSError:
            pass


# Env-activated tracing+latency stream for the --latency gate:
# SLATE_TPU_METRICS + SLATE_TPU_TRACE_RING are read at import (the
# production activation path); faults are armed AFTER warmup (an
# execute fault during warmup would fail the precompile by design).
# The driver asserts the ISSUE acceptance inline: every delivered
# request's trace is a complete admit -> deliver span chain in the
# Chrome export, and a retried request carries a backoff span.
_LATENCY_DRIVER = """
import json
import sys
import numpy as np
from slate_tpu.aux import faults, metrics, spans
from slate_tpu.exceptions import SlateError
from slate_tpu.serve import buckets as bk
from slate_tpu.serve.cache import ExecutableCache
from slate_tpu.serve.service import SolverService

trace_path = sys.argv[1]
assert spans.is_on() and spans.capacity() >= 4096  # env armed the ring
svc = SolverService(cache=ExecutableCache(manifest_path=None), batch_max=4,
                    batch_window_s=0.002, dim_floor=16, nrhs_floor=4,
                    retry_backoff_s=0.002, breaker_cooldown_s=0.05,
                    retry_seed=0)
k1 = bk.bucket_for("gesv", 12, 12, 2, np.float64, floor=16, nrhs_floor=4)
k2 = bk.bucket_for("posv", 24, 24, 2, np.float64, floor=16, nrhs_floor=4)
svc.cache.ensure_manifest(k1, (1, 4))
svc.cache.ensure_manifest(k2, (1, 4))
svc.warmup()  # warmed: the latency split measures serving, not compiles
# latency+execute injection (ISSUE acceptance): every=6 is
# deterministic — at least one batch fails and retries with backoff
faults.configure("execute:every=6;latency:p=0.3,ms=5,seed=5")
faults.on()

def prob(rt, n, seed):
    r = np.random.default_rng(seed)
    A = r.standard_normal((n, n))
    A = A @ A.T + n * np.eye(n) if rt == "posv" else A + n * np.eye(n)
    return rt, A, r.standard_normal((n, 2))

probs = [prob("gesv", 12, i) for i in range(16)] + [
    prob("posv", 24, 100 + i) for i in range(8)]
futs = [svc.submit(rt, A, B, deadline=120.0, retries=3)
        for rt, A, B in probs]
ok = typed = 0
for f in futs:
    try:
        X = f.result(timeout=300)
        assert np.all(np.isfinite(X))
        ok += 1
    except SlateError:
        typed += 1  # retry budget exhausted into a faulted direct path
assert ok + typed == len(futs), "a future hung"
assert ok >= len(futs) - 4, f"too many failures: {ok}/{len(futs)}"
faults.reset()
svc.stop()
spans.export_chrome(trace_path)

data = json.load(open(trace_path))
evs = [e for e in data["traceEvents"] if e.get("ph") in ("X", "i")]
traces = {}
for e in evs:
    tr = e.get("args", {}).get("trace")
    if tr:
        traces.setdefault(tr, {}).setdefault(e["name"], []).append(e)
roots = {tr: t["request"][0] for tr, t in traces.items() if "request" in t}
orphans = sorted(tr for tr in traces if tr not in roots)
assert not orphans, f"orphan traces (no request root): {orphans}"
delivered = {tr: r for tr, r in roots.items()
             if r["args"].get("outcome") == "ok"}
assert len(delivered) == ok, (len(delivered), ok)
for tr in delivered:
    names = set(traces[tr])
    assert "admit" in names and "queued" in names, (tr, names)
    assert "execute" in names or "direct" in names, (tr, names)
retried = [tr for tr in traces if "backoff" in traces[tr]]
assert retried, "execute faults fired but no backoff span recorded"
h = svc.health()
assert h["latency"], "health() must surface per-bucket percentiles"
print(f"latency driver: {ok} delivered, {typed} typed, "
      f"{len(delivered)} complete span chains, {len(retried)} retried "
      f"with backoff spans, 0 orphans")
"""


def latency_gate() -> int:
    """Latency/tracing gate, three legs: (1) the span + histogram
    suites; (2) an env-activated warmed serve stream under
    latency+execute fault injection (SLATE_TPU_METRICS +
    SLATE_TPU_TRACE_RING, the production activation path) that exports
    a Chrome trace and asserts every delivered request has a complete
    admit -> deliver span chain; (3) tools/latency_report.py over the
    stream's JSONL — per-bucket p50/p95/p99 with the queued-vs-execute
    split, failing past the p99 budget."""
    import tempfile

    here = os.path.dirname(os.path.abspath(__file__)) or "."
    rc = subprocess.call(
        [sys.executable, "-m", "pytest", "tests/test_spans.py",
         "tests/test_metrics.py", "-q",
         "-p", "no:cacheprovider", "-p", "no:xdist", "-p", "no:randomly"],
        env=dict(os.environ, JAX_PLATFORMS="cpu"), cwd=here,
    )
    if rc != 0:
        return rc
    with tempfile.TemporaryDirectory(prefix="slate_latency_") as td:
        jsonl = os.path.join(td, "latency.jsonl")
        trace_json = os.path.join(td, "trace.json")
        env = dict(
            os.environ, JAX_PLATFORMS="cpu", SLATE_TPU_METRICS=jsonl,
            SLATE_TPU_TRACE_RING="8192",
        )
        env.pop("SLATE_TPU_FAULTS", None)  # the driver arms post-warmup
        rc = subprocess.call(
            [sys.executable, "-c", _LATENCY_DRIVER, trace_json],
            env=env, cwd=here,
        )
        if rc != 0:
            return rc
        return subprocess.call(
            [sys.executable, os.path.join("tools", "latency_report.py"),
             jsonl, "--p99-budget", "30"],
            cwd=here,
        )


# Env-activated repeated-A stream for the --factor gate:
# SLATE_TPU_FACTOR_CACHE=1 + SLATE_TPU_METRICS are read at import (the
# production activation path).  One submit factors and caches; the
# warmed 20-request same-A stream must be trsm-only (hits) and
# compile-free; the JSONL is joined by tools/factor_report.py.
_FACTOR_DRIVER = """
import jax
jax.config.update("jax_enable_x64", True)
import numpy as np
from slate_tpu.aux import metrics
from slate_tpu.serve.cache import ExecutableCache
from slate_tpu.serve.service import SolverService

svc = SolverService(cache=ExecutableCache(manifest_path=None), batch_max=4,
                    batch_window_s=0.002, dim_floor=16, nrhs_floor=4)
assert svc.factor_cache is not None, "SLATE_TPU_FACTOR_CACHE must arm it"
rng = np.random.default_rng(0)
n = 12
A = rng.standard_normal((n, n)) + n * np.eye(n)
B0 = rng.standard_normal((n, 2))
X0 = svc.submit("gesv", A, B0).result(timeout=300)
assert np.abs(X0 - np.linalg.solve(A, B0)).max() < 1e-9
svc.warmup()  # the miss registered the solve bucket; precompile it
with metrics.deltas() as d:
    futs = [svc.submit("gesv", A, rng.standard_normal((n, 2)))
            for _ in range(20)]
    for f in futs:
        assert np.all(np.isfinite(f.result(timeout=300)))
    hits = d.get("serve.factor_cache.hit")
    comp = d.get("jit.compilations")
assert hits >= 19, hits
assert comp == 0, f"warmed repeated-A stream compiled: {comp}"
svc.stop()
print(f"factor driver: 1 factor + 20 trsm-only solves, "
      f"{int(hits)} hits, 0 compiles")
"""


def factor_gate() -> int:
    """Factor-cache gate, two legs: (1) the factor-cache suite
    (keying, budgets, up/downdate, solve-phase manifest/artifact
    round-trips, the warmed repeated-A acceptance stream); (2) an
    env-activated repeated-A stream (SLATE_TPU_FACTOR_CACHE=1 +
    SLATE_TPU_METRICS, the production activation path) whose JSONL is
    joined by tools/factor_report.py — a repeated-A stream with zero
    hits fails the gate."""
    import tempfile

    here = os.path.dirname(os.path.abspath(__file__)) or "."
    rc = subprocess.call(
        [sys.executable, "-m", "pytest", "tests/test_factor_cache.py",
         "-q",
         "-p", "no:cacheprovider", "-p", "no:xdist", "-p", "no:randomly"],
        env=dict(os.environ, JAX_PLATFORMS="cpu"), cwd=here,
    )
    if rc != 0:
        return rc
    jsonl = os.path.join(
        tempfile.gettempdir(), f"factor_{os.getpid()}.jsonl"
    )
    env = dict(
        os.environ, JAX_PLATFORMS="cpu", SLATE_TPU_METRICS=jsonl,
        SLATE_TPU_FACTOR_CACHE="1",
    )
    env.pop("SLATE_TPU_FAULTS", None)
    try:
        rc = subprocess.call(
            [sys.executable, "-c", _FACTOR_DRIVER], env=env, cwd=here
        )
        if rc != 0:
            return rc
        return subprocess.call(
            [sys.executable, os.path.join("tools", "factor_report.py"),
             jsonl],
            cwd=here,
        )
    finally:
        try:
            os.unlink(jsonl)
        except OSError:
            pass


# Env-activated repeated-A gels stream for the --fabric gate:
# SLATE_TPU_FACTOR_CACHE=1 (+ SLATE_TPU_FACTOR_ARENA=1 on the armed
# leg) are read at service construction — the production activation
# path.  One gels submit factors the QR pack and caches it; the warmed
# >= 20-solve pristine-session stream must be hits-only, compile-free
# and (armed) upload-free; a streamed append + fenced CSNE solve
# closes the loop.  Every X lands in argv[1] so the gate can prove the
# arena-off leg byte-identical to the armed one.
_FABRIC_DRIVER = """
import os
import sys
import jax
jax.config.update("jax_enable_x64", True)
import numpy as np
from slate_tpu.aux import metrics
from slate_tpu.serve.cache import ExecutableCache
from slate_tpu.serve.service import SolverService
from slate_tpu.fabric.session import FactorSession

out = sys.argv[1]
svc = SolverService(cache=ExecutableCache(manifest_path=None), batch_max=4,
                    batch_window_s=0.002, dim_floor=16, nrhs_floor=4)
assert svc.factor_cache is not None, "SLATE_TPU_FACTOR_CACHE must arm it"
want_arena = bool(os.environ.get("SLATE_TPU_FACTOR_ARENA"))
assert (svc.arena is not None) == want_arena, svc.arena
rng = np.random.default_rng(0)
m, n = 40, 12
A = rng.standard_normal((m, n))
B0 = rng.standard_normal((m, 2))
X0 = svc.submit("gels", A, B0).result(timeout=300)
assert np.abs(X0 - np.linalg.lstsq(A, B0, rcond=None)[0]).max() < 1e-9
svc.warmup()  # the miss registered the gels solve bucket; precompile it
sess = FactorSession(svc, A)
Bs = [rng.standard_normal((m, 2)) for _ in range(20)]
with metrics.deltas() as d:
    Xs = [sess.solve(B) for B in Bs]
    hits = int(d.get("serve.factor_cache.hit") or 0)
    comp = int(d.get("jit.compilations") or 0)
    avoided = int(d.get("serve.arena.upload_avoided_bytes") or 0)
assert hits >= 19, hits
assert comp == 0, f"warmed gels session stream compiled: {comp}"
if want_arena:
    assert avoided > 0, "arena armed but every hit still re-uploaded"
else:
    assert avoided == 0, "arena unarmed but arena counters moved"
C = rng.standard_normal((5, n))
sess.append(C)
B2 = rng.standard_normal((m + 5, 2))
X2 = sess.solve(B2)
ref = np.linalg.lstsq(np.vstack([A, C]), B2, rcond=None)[0]
assert np.abs(X2 - ref).max() < 1e-9, "streamed session solve drifted"
np.save(out, np.stack([X0, *Xs, X2]))
svc.stop()
print(f"fabric driver[arena={'on' if want_arena else 'off'}]: 1 factor "
      f"+ {len(Xs)} session solves, {hits} hits, 0 compiles, "
      f"upload_avoided={avoided}")
"""


def fabric_gate() -> int:
    """Factor-fabric gate, three legs: (1) the fabric suite (arena
    budgets/spill/cross-replica, session update-vs-refactor parity,
    breakdown refactor, fence coverage); (2) the env-activated
    repeated-A gels stream with the arena ARMED (factor once, >= 20
    warmed session solves, 0 compiles, upload_avoided_bytes > 0),
    judged by tools/factor_report.py; (3) the same stream with the
    arena OFF, whose every X must be byte-identical to leg 2's —
    the unarmed service is provably legacy."""
    import tempfile

    here = os.path.dirname(os.path.abspath(__file__)) or "."
    rc = subprocess.call(
        [sys.executable, "-m", "pytest", "tests/test_fabric.py", "-q",
         "-p", "no:cacheprovider", "-p", "no:xdist", "-p", "no:randomly"],
        env=dict(os.environ, JAX_PLATFORMS="cpu"), cwd=here,
    )
    if rc != 0:
        return rc
    tmp = tempfile.gettempdir()
    jsonl = os.path.join(tmp, f"fabric_{os.getpid()}.jsonl")
    jsonl_off = os.path.join(tmp, f"fabric_off_{os.getpid()}.jsonl")
    out_on = os.path.join(tmp, f"fabric_on_{os.getpid()}.npy")
    out_off = os.path.join(tmp, f"fabric_off_{os.getpid()}.npy")
    base = dict(os.environ, JAX_PLATFORMS="cpu",
                SLATE_TPU_FACTOR_CACHE="1")
    for k in ("SLATE_TPU_FAULTS", "SLATE_TPU_FACTOR_ARENA",
              "SLATE_TPU_METRICS"):
        base.pop(k, None)
    try:
        rc = subprocess.call(
            [sys.executable, "-c", _FABRIC_DRIVER, out_on],
            env=dict(base, SLATE_TPU_METRICS=jsonl,
                     SLATE_TPU_FACTOR_ARENA="1"),
            cwd=here,
        )
        if rc != 0:
            return rc
        rc = subprocess.call(
            [sys.executable, "-c", _FABRIC_DRIVER, out_off],
            # metrics on (the driver asserts hit counters) but the
            # arena env stays popped — this is the legacy leg
            env=dict(base, SLATE_TPU_METRICS=jsonl_off), cwd=here,
        )
        if rc != 0:
            return rc
        import numpy as np

        a, b = np.load(out_on), np.load(out_off)
        if a.dtype != b.dtype or a.shape != b.shape \
                or a.tobytes() != b.tobytes():
            print("FABRIC GATE: arena-off X stream is not "
                  "byte-identical to the armed leg — the unarmed "
                  "service is not legacy")
            return 1
        print("fabric gate: arena-off leg byte-identical to armed leg")
        return subprocess.call(
            [sys.executable, os.path.join("tools", "factor_report.py"),
             jsonl],
            cwd=here,
        )
    finally:
        for p in (jsonl, jsonl_off, out_on, out_off):
            try:
                os.unlink(p)
            except OSError:
                pass


# Two-leg bursty two-tenant stream for the --adaptive gate.  Same
# phase-1 trace both legs: an abusive tenant floods 48 requests, then a
# well-behaved tenant submits 8 on its own bucket; every dispatch pays
# a deterministic injected 30 ms (machine-independent queueing).  The
# STATIC leg (tenancy/adaptation off — tags accepted but inert) must
# PROVABLY miss the well-behaved p99 budget: the flood head-of-line
# blocks the shared FIFO.  The ADAPTIVE leg (tenant quotas + WFQ +
# adaptive window) must hold it, then two overload phases (tight-
# deadline abuser traffic driving the burn EWMA up) must end in typed
# Shed refusals — every admitted future still resolves.
_ADAPTIVE_DRIVER = """
import sys
import time
import jax
jax.config.update("jax_enable_x64", True)
import numpy as np
from slate_tpu.aux import faults, metrics
from slate_tpu.exceptions import SlateError
from slate_tpu.serve import buckets as bk
from slate_tpu.serve.cache import ExecutableCache
from slate_tpu.serve.service import Rejected, Shed, SolverService

mode = sys.argv[1]  # "static" | "adaptive"
BUDGET = 0.25
n_good, n_abuse = 24, 12  # distinct buckets: the flood never coalesces
                          # with the victim's traffic

kw = dict(cache=ExecutableCache(manifest_path=None), batch_max=4,
          batch_window_s=0.01, dim_floor=16, nrhs_floor=4)
if mode == "adaptive":
    kw.update(
        tenants="good:weight=4;abuser:rate=10,burst=4,share=0.25",
        adaptive=True, latency_budget_s=BUDGET,
    )
svc = SolverService(**kw)
k_good = bk.bucket_for("gesv", n_good, n_good, 2, np.float64, floor=16,
                       nrhs_floor=4)
k_abuse = bk.bucket_for("gesv", n_abuse, n_abuse, 2, np.float64, floor=16,
                        nrhs_floor=4)
svc.cache.ensure_manifest(k_good, (1, 4))
svc.cache.ensure_manifest(k_abuse, (1, 4))
svc.warmup()  # the burst measures queueing, not compiles
faults.configure("latency:every=1,ms=30")  # armed POST-warmup
faults.on()

def prob(n, seed):
    r = np.random.default_rng(seed)
    return (r.standard_normal((n, n)) + n * np.eye(n),
            r.standard_normal((n, 2)))

A_a, B_a = prob(n_abuse, 1)
futs, shed, rejected = [], 0, 0

def sub(**skw):
    global shed, rejected
    try:
        futs.append(svc.submit("gesv", A_a, B_a, tenant="abuser",
                               priority="low", **skw))
    except Shed:
        shed += 1
    except Rejected:
        rejected += 1

for _ in range(48):  # phase 1: the flood...
    sub()
for i in range(8):  # ...then the victim
    A, B = prob(n_good, 100 + i)
    futs.append(svc.submit("gesv", A, B, tenant="good", priority="high",
                           deadline=10.0))
if mode == "adaptive":
    # phase 2: tight-deadline abuser traffic melts its own SLO — the
    # burn EWMA climbs; phase 3: the controller must be shedding
    time.sleep(0.4)  # tokens refill (~4), phase-1 queue drains
    for _ in range(8):
        sub(deadline=0.02)
    deadline = time.monotonic() + 10.0
    while shed == 0 and time.monotonic() < deadline:
        time.sleep(0.05)
        sub(deadline=0.02)
ok = typed = 0
for f in futs:
    try:
        assert np.all(np.isfinite(f.result(timeout=300)))
        ok += 1
    except SlateError:
        typed += 1
assert ok + typed == len(futs), "a future hung"
faults.reset()
h = svc.health()
svc.stop()
p99_good_bucket = metrics.percentile(
    f"serve.latency.{k_good.label}.total", 99)
if mode == "static":
    assert p99_good_bucket is not None and p99_good_bucket > BUDGET, (
        "static config should have missed the %.0f ms budget, got %s"
        % (BUDGET * 1e3, p99_good_bucket))
    print(f"adaptive driver [static]: victim p99 "
          f"{p99_good_bucket * 1e3:.0f} ms MISSES the "
          f"{BUDGET * 1e3:.0f} ms budget (as designed), "
          f"{ok} delivered / {typed} typed")
else:
    p99_good = metrics.percentile("serve.latency.tenant.good.total", 99)
    assert p99_good is not None and p99_good <= BUDGET, (
        "adaptive config missed the victim budget: %s" % p99_good)
    assert shed > 0, "overload never shed the abuser"
    assert rejected > 0, "the abuser quota never rejected"
    assert h["tenants"]["abuser"]["shed"] == shed
    assert h["admission"]["overload_level"] >= 1, h["admission"]
    assert any(k_abuse.label in k or k_good.label in k
               for k in h["admission"]["windows"]), h["admission"]
    print(f"adaptive driver [adaptive]: victim p99 "
          f"{p99_good * 1e3:.0f} ms holds the {BUDGET * 1e3:.0f} ms "
          f"budget; abuser shed={shed} quota-rejected={rejected}; "
          f"{ok} delivered / {typed} typed, 0 hangs")
"""

# tenant_flood chaos leg: the site is armed via env (the production
# activation path), one real submit triggers a synthetic 24-request
# low-priority burst from tenant "flood", whose tight quota refuses
# most of it — chaos_report then joins faults.injected.tenant_flood
# against the serve.shed/serve.rejected* recovery family.
_FLOOD_DRIVER = """
import jax
jax.config.update("jax_enable_x64", True)
import numpy as np
from slate_tpu.aux import metrics
from slate_tpu.serve.cache import ExecutableCache
from slate_tpu.serve.service import SolverService

svc = SolverService(cache=ExecutableCache(manifest_path=None), batch_max=4,
                    dim_floor=16, nrhs_floor=4)
assert svc._admission is not None, "SLATE_TPU_TENANTS must arm the plane"
rng = np.random.default_rng(0)
n = 12
A = rng.standard_normal((n, n)) + n * np.eye(n)
B = rng.standard_normal((n, 2))
X = svc.submit("gesv", A, B, tenant="good").result(timeout=300)
assert np.abs(X - np.linalg.solve(A, B)).max() < 1e-9
c = metrics.counters()
assert c.get("faults.injected.tenant_flood", 0) >= 1, c
refused = c.get("serve.rejected", 0) + c.get("serve.shed", 0)
assert refused >= 1, "the flood burst was never refused"
svc.stop()
print(f"flood driver: 1 real request delivered, synthetic burst "
      f"refused {int(refused)}x")
"""


def adaptive_gate() -> int:
    """Admission/fairness gate, three legs: (1) the admission suite
    (fake-clock controller units + the fairness invariant); (2) the
    two-leg bursty two-tenant stream — the static config must
    provably MISS the well-behaved tenant's p99 budget while the
    adaptive config holds it, sheds the abuser, and resolves every
    future typed — with tools/tenant_report.py rendering the
    per-tenant verdict from the adaptive leg's JSONL; (3) a
    tenant_flood chaos leg joined by tools/chaos_report.py."""
    import tempfile

    here = os.path.dirname(os.path.abspath(__file__)) or "."
    rc = subprocess.call(
        [sys.executable, "-m", "pytest", "tests/test_admission.py", "-q",
         "-p", "no:cacheprovider", "-p", "no:xdist", "-p", "no:randomly"],
        env=dict(os.environ, JAX_PLATFORMS="cpu"), cwd=here,
    )
    if rc != 0:
        return rc
    with tempfile.TemporaryDirectory(prefix="slate_adaptive_") as td:
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        for var in ("SLATE_TPU_FAULTS", "SLATE_TPU_TENANTS",
                    "SLATE_TPU_ADAPTIVE", "SLATE_TPU_FACTOR_CACHE"):
            env.pop(var, None)
        # leg 2a: static config — the driver asserts the budget MISS
        rc = subprocess.call(
            [sys.executable, "-c", _ADAPTIVE_DRIVER, "static"],
            env=dict(env, SLATE_TPU_METRICS=os.path.join(td, "static.jsonl")),
            cwd=here,
        )
        if rc != 0:
            return rc
        # leg 2b: adaptive config — holds the budget, sheds the abuser
        jsonl = os.path.join(td, "adaptive.jsonl")
        rc = subprocess.call(
            [sys.executable, "-c", _ADAPTIVE_DRIVER, "adaptive"],
            env=dict(env, SLATE_TPU_METRICS=jsonl), cwd=here,
        )
        if rc != 0:
            return rc
        rc = subprocess.call(
            [sys.executable, os.path.join("tools", "tenant_report.py"),
             jsonl, "--p99-budget", "0.25", "--well-behaved", "good",
             "--abusive", "abuser"],
            cwd=here,
        )
        if rc != 0:
            return rc
        # leg 3: tenant_flood chaos attribution
        flood = os.path.join(td, "flood.jsonl")
        rc = subprocess.call(
            [sys.executable, "-c", _FLOOD_DRIVER],
            env=dict(
                env, SLATE_TPU_METRICS=flood,
                SLATE_TPU_TENANTS="flood:rate=1,burst=2,share=0.1",
                SLATE_TPU_FAULTS="tenant_flood:once,burst=24",
            ),
            cwd=here,
        )
        if rc != 0:
            return rc
        return subprocess.call(
            [sys.executable, os.path.join("tools", "chaos_report.py"),
             flood],
            cwd=here,
        )


# Restart-drill drivers for the --coldstart gate.  Each runs in its OWN
# subprocess so the restore leg is a true fresh interpreter: nothing
# carries over but the artifact dir + manifest on disk.

_COLDSTART_WARM = """
import sys
import jax
jax.config.update("jax_enable_x64", True)
import numpy as np
from slate_tpu.serve.cache import ExecutableCache
from slate_tpu.serve.service import SolverService

art, man = sys.argv[1], sys.argv[2]
rng = np.random.default_rng(0)
n1, n2 = 10, 20
A1 = rng.standard_normal((n1, n1)) + n1 * np.eye(n1)
B1 = rng.standard_normal((n1, 2))
G = rng.standard_normal((n2, n2))
A2 = G @ G.T + n2 * np.eye(n2)
B2 = rng.standard_normal((n2, 3))

cache = ExecutableCache(manifest_path=man, artifact_dir=art)
# schedule="recursive": pure-JAX kernels whose exported modules are
# custom-call free, so every bucket lands on the export rung (auto
# routes to vendor LAPACK on CPU -> cache_seed, no zero-compile leg)
svc = SolverService(cache=cache, batch_max=4, batch_window_s=0.005,
                    dim_floor=16, nrhs_floor=4, schedule="recursive")
assert svc.wait_ready(120), svc.health()
futs = [svc.submit("gesv", A1 + i * 0.01 * np.eye(n1), B1)
        for i in range(4)]
futs += [svc.submit("posv", A2, B2)]
for f in futs:
    assert np.all(np.isfinite(f.result(timeout=300)))
# build + persist BOTH batch points of both buckets (traffic above
# registered them in the manifest; warmup bakes the rest to artifacts)
compiled = cache.warmup(batch_max=4)
svc.stop()
import os
n_art = len([f for f in os.listdir(art) if f.endswith(".slate_exe")])
assert n_art >= 4, f"expected >= 4 artifacts, found {n_art}"
print(f"coldstart warm: {compiled} warmup compiles, {n_art} artifacts")
"""

_COLDSTART_RESTORE = """
import sys
import jax
jax.config.update("jax_enable_x64", True)
import numpy as np
from slate_tpu.aux import metrics
from slate_tpu.serve.cache import ExecutableCache
from slate_tpu.serve.service import SolverService

art, man, leg = sys.argv[1], sys.argv[2], sys.argv[3]
rng = np.random.default_rng(1)
n1, n2 = 10, 20
A1 = rng.standard_normal((n1, n1)) + n1 * np.eye(n1)
B1 = rng.standard_normal((n1, 2))
G = rng.standard_normal((n2, n2))
A2 = G @ G.T + n2 * np.eye(n2)
B2 = rng.standard_normal((n2, 3))

cache = ExecutableCache(manifest_path=man, artifact_dir=art)
svc = SolverService(cache=cache, batch_max=4, batch_window_s=0.005,
                    dim_floor=16, nrhs_floor=4,
                    schedule="recursive")  # restores on start
assert svc.wait_ready(300), svc.health()
h = svc.health()
assert h["ready"] and h["phase"] == "ready", h
res = h["restore"]
assert res is not None and res["failed"] == 0, res
if leg == "clean":
    # every entry must come from a verified artifact, zero recompiles
    assert res["compiled"] == 0 and res["restored"] >= 4, res
elif leg == "flipped":
    # the byte-flipped artifact must be detected and recompiled
    assert res["compiled"] >= 1, res
    assert metrics.counters().get("serve.artifact_corrupt", 0) >= 1
elif leg == "chaos":
    # once-per-site injection: corrupt, stale, load_fail each eat one
    # load; the fourth restores clean
    assert res["compiled"] == 3 and res["restored"] == 1, res

with metrics.deltas() as d:
    futs = []
    for i in range(4):
        futs.append(svc.submit("gesv", A1 + i * 1e-3 * np.eye(n1), B1))
        futs.append(svc.submit("posv", A2 + i * 1e-3 * np.eye(n2), B2))
    for f in futs:
        assert np.all(np.isfinite(f.result(timeout=300)))
    for i in range(12):
        X1 = svc.submit("gesv", A1, B1).result(timeout=300)
    X2 = svc.submit("posv", A2, B2).result(timeout=300)
    assert d.get("serve.requests") >= 20
    assert d.get("jit.compilations") == 0, (
        "restored steady state must not compile: "
        f"{d.get('jit.compilations')}")
svc.stop()
# correctness vs numpy (no slate dispatch: keeps the window honest)
assert np.abs(X1 - np.linalg.solve(A1, B1)).max() < 1e-9
assert np.abs(X2 - np.linalg.solve(A2, B2)).max() < 1e-9
print(f"coldstart {leg}: ready via {res}, "
      f"{int(d.get('serve.requests'))} requests, 0 compiles"
      if leg == "clean" else
      f"coldstart {leg}: ready via {res}, recovered correctly")
"""


def coldstart() -> int:
    """Cold-start gate, three legs sharing one artifact dir: (1) the
    artifact suite; (2) the ISSUE restart drill — warm a service in
    one process, restore in a FRESH process with zero compiles in a
    >= 20-request steady-state stream, then byte-flip one artifact and
    drill again expecting a counted recompile; (3) a chaos pass arming
    the three artifact fault sites, gated by tools/artifact_report.py
    (nonzero when any injected fault escaped verification)."""
    import tempfile

    here = os.path.dirname(os.path.abspath(__file__)) or "."
    rc = subprocess.call(
        [sys.executable, "-m", "pytest", "tests/test_artifacts.py", "-q",
         "-p", "no:cacheprovider", "-p", "no:xdist", "-p", "no:randomly"],
        env=dict(os.environ, JAX_PLATFORMS="cpu"), cwd=here,
    )
    if rc != 0:
        return rc
    with tempfile.TemporaryDirectory(prefix="slate_coldstart_") as td:
        art = os.path.join(td, "artifacts")
        man = os.path.join(td, "warmup.json")
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("SLATE_TPU_FAULTS", None)

        def run(code, *argv, **extra_env):
            e = dict(env, **extra_env)
            return subprocess.call(
                [sys.executable, "-c", code, *argv], env=e, cwd=here
            )

        rc = run(_COLDSTART_WARM, art, man)
        if rc != 0:
            return rc
        rc = run(_COLDSTART_RESTORE, art, man, "clean",
                 SLATE_TPU_METRICS=os.path.join(td, "clean.jsonl"))
        if rc != 0:
            return rc
        # byte-flip drill: corrupt one artifact payload on disk
        victims = sorted(
            f for f in os.listdir(art) if f.endswith(".slate_exe")
        )
        path = os.path.join(art, victims[0])
        blob = bytearray(open(path, "rb").read())
        blob[-3] ^= 0xFF
        open(path, "wb").write(bytes(blob))
        rc = run(_COLDSTART_RESTORE, art, man, "flipped",
                 SLATE_TPU_METRICS=os.path.join(td, "flipped.jsonl"))
        if rc != 0:
            return rc
        # chaos leg: every artifact fault site injected once, then the
        # report joins injected-vs-detected from the JSONL
        jsonl = os.path.join(td, "chaos.jsonl")
        rc = run(
            _COLDSTART_RESTORE, art, man, "chaos",
            SLATE_TPU_METRICS=jsonl,
            SLATE_TPU_FAULTS=(
                "artifact_corrupt:once;artifact_stale:once;"
                "artifact_load_fail:once"
            ),
        )
        if rc != 0:
            return rc
        return subprocess.call(
            [sys.executable, os.path.join("tools", "artifact_report.py"),
             jsonl],
            cwd=here,
        )


# Env-activated device-telemetry stream for the --perf gate:
# SLATE_TPU_DEVMON=1 + SLATE_TPU_METRICS are read at import (the
# production activation path).  A warmed mixed-shape stream must yield
# health() cost/memory evidence for EVERY warmed bucket (the ISSUE
# acceptance), a graceful device snapshot on CPU (byte fields None,
# never a crash), and stay compile-free; the JSONL is then judged by
# tools/roofline_report.py.
_PERF_DRIVER = """
import jax
jax.config.update("jax_enable_x64", True)
import numpy as np
from slate_tpu.aux import devmon, metrics
from slate_tpu.serve import buckets as bk
from slate_tpu.serve.cache import ExecutableCache
from slate_tpu.serve.service import SolverService

assert devmon.is_on(), "SLATE_TPU_DEVMON must arm the telemetry plane"
svc = SolverService(cache=ExecutableCache(manifest_path=None), batch_max=4,
                    batch_window_s=0.002, dim_floor=16, nrhs_floor=4)
k1 = bk.bucket_for("gesv", 12, 12, 2, np.float64, floor=16, nrhs_floor=4)
k2 = bk.bucket_for("posv", 24, 24, 2, np.float64, floor=16, nrhs_floor=4)
svc.cache.ensure_manifest(k1, (1, 4))
svc.cache.ensure_manifest(k2, (1, 4))
svc.warmup()  # cold builds: the registry captures here

def prob(rt, n, seed):
    r = np.random.default_rng(seed)
    A = r.standard_normal((n, n))
    A = A @ A.T + n * np.eye(n) if rt == "posv" else A + n * np.eye(n)
    return rt, A, r.standard_normal((n, 2))

probs = [prob("gesv", 12, i) for i in range(16)] + [
    prob("posv", 24, 100 + i) for i in range(8)]
with metrics.deltas() as d:
    futs = [svc.submit(rt, A, B) for rt, A, B in probs]
    for f in futs:
        assert np.all(np.isfinite(f.result(timeout=300)))
    assert d.get("jit.compilations") == 0, (
        "warmed telemetry stream compiled: %d" % d.get("jit.compilations"))

h = svc.health()
for lbl in (k1.label, k2.label):
    per = (h["cost"] or {}).get(lbl)
    assert per, (lbl, h["cost"])
    for b, c in per.items():
        assert c.get("flops", 0) > 0 and c.get("peak_bytes", 0) > 0, (
            lbl, b, c)
    assert h["latency"][lbl]["peak_bytes"] > 0, h["latency"][lbl]
assert isinstance(h["devices"], list) and h["devices"], h["devices"]
for dev in h["devices"]:  # CPU: graceful None, never a crash
    assert "bytes_in_use" in dev, dev
print(f"perf driver: {len(probs)} warmed requests over "
      f"{len(h['cost'])} buckets with cost/memory evidence, 0 compiles")
svc.stop()
"""


# Interpret-mode Pallas leg: CPU CI runs every panel kernel of the
# ``pallas`` schedule family through pl.pallas_call(..., interpret=True)
# against its jnp reference twin — the family is gated without real
# chips (the compiled Mosaic path shares the SAME kernel bodies).
_PALLAS_PANEL_DRIVER = """
import jax
jax.config.update("jax_enable_x64", True)
import numpy as np
import jax.numpy as jnp
from jax import lax
from slate_tpu.ops.pallas import panel_kernels as pk
from slate_tpu.ops.qr_fast import _qr_panel_strips
from slate_tpu.ops.householder import materialize_v

rng = np.random.default_rng(0)
checked = 0
for dt in (np.float32, np.float64, np.complex64, np.complex128):
    tol = 5e3 * np.finfo(np.dtype(dt)).eps

    def rand(shape):
        x = rng.standard_normal(shape)
        if np.issubdtype(dt, np.complexfloating):
            x = x + 1j * rng.standard_normal(shape)
        return jnp.asarray(x, dt)

    def close(a, b, exact=False):
        global checked
        checked += 1
        err = float(jnp.max(jnp.abs(a - b)))
        ref = max(float(jnp.max(jnp.abs(b))), 1.0)
        lim = 0.0 if exact else tol * ref
        assert err <= lim, (np.dtype(dt).name, checked, err, lim)

    b = 64
    A = rand((b, b)); G = A @ jnp.conj(A).T + b * jnp.eye(b, dtype=dt)
    close(jnp.tril(pk.chol_base_pallas(G, interpret=True)),
          jnp.tril(pk.chol_base_reference(G)))
    for M, w, act in ((96, 32, None), (96, 32, 80), (160, 24, None)):
        P = rand((M, w))
        lu_p, p_p = pk.panel_lu_pallas(P, act=act, interpret=True)
        lu_r, p_r = pk.panel_lu_reference(P, act=act)
        close(lu_p, lu_r, exact=True)
        assert bool(jnp.all(p_p == p_r)), "pivot order drifted"
    Pn = rand((96, 32))
    Vp, taus = _qr_panel_strips(Pn, 16)
    V = materialize_v(Vp)
    close(pk.larft_pallas(V, taus, interpret=True),
          pk.larft_reference(V, taus), exact=True)
    C = rand((48, 48)); Aa = rand((48, 24))
    close(pk.syrk_diag_pallas(C, Aa, interpret=True),
          pk.syrk_diag_reference(C, Aa), exact=True)
    C2 = rand((48, 40)); Bb = rand((40, 24))
    close(pk.gemm_sub_pallas(C2, Aa, Bb, interpret=True),
          pk.gemm_sub_reference(C2, Aa, Bb), exact=True)
    n, nrhs = 128, 16
    B = rand((n, nrhs))
    L = jnp.tril(rand((n, n)), -1) * 0.3 + jnp.diag(
        jnp.asarray(2.0 + rng.random(n), dt))
    close(pk.trsm_lower_pallas(L, B, interpret=True),
          pk.trsm_lower_reference(L, B))
    Lu = jnp.tril(rand((n, n)), -1) * 0.3 + jnp.eye(n, dtype=dt)
    close(pk.trsm_lower_pallas(Lu, B, unit=True, interpret=True),
          pk.trsm_lower_reference(Lu, B, unit=True))
    U = jnp.triu(rand((n, n)), 1) * 0.3 + jnp.diag(
        jnp.asarray(2.0 + rng.random(n), dt))
    close(pk.trsm_upper_pallas(U, B, interpret=True),
          pk.trsm_upper_reference(U, B))
print(f"pallas interpret leg: {checked} kernel/dtype parity checks green")
"""


def perf_gate() -> int:
    """Perf gate, five legs: (1) the devmon suite; (2) the interpret-
    mode Pallas leg — every panel kernel of the ``pallas`` schedule
    family runs via ``pl.pallas_call(..., interpret=True)`` against its
    jnp twin on CPU (f32/f64/c64/c128, act-masked + non-pow2 panels,
    exact pivot order); (3) the regression sentinel on the checked-in
    trajectory — the true BENCH_r03 -> BENCH_r04 pair passes while a
    synthetically-regressed copy of r04 exits nonzero; (4) an
    env-activated devmon serve stream whose JSONL
    tools/roofline_report.py must classify (nonzero on any
    unclassifiable warmed bucket — the warmed solve buckets included);
    (5) a quick warmed bench leg diffed ``--floor`` against the
    checked-in BENCH_FLOOR_CPU.json (dtrsm solve-phase entries
    included)."""
    import json
    import tempfile

    here = os.path.dirname(os.path.abspath(__file__)) or "."
    # ONE scrubbed env for every leg: a chaos env armed at import
    # would inject into warmup builds and the bench leg's serve
    # entries, an env-armed factor cache detours streams off the
    # bucket-build path, a deployment's peaks override would shift
    # the suite's default-table assertions and every roofline verdict,
    # and — worst — an inherited SLATE_TPU_WARMUP/ARTIFACTS would
    # attach the gate's CPU builds to the operator's PRODUCTION
    # manifest/store and overwrite its captured evidence (an inherited
    # SLATE_TPU_METRICS likewise clobbers the operator's JSONL at
    # every subprocess exit).  This gate measures perf against
    # hermetic defaults; legs that need metrics/devmon set their own.
    tenv = dict(os.environ, JAX_PLATFORMS="cpu")
    for var in ("SLATE_TPU_FAULTS", "SLATE_TPU_FACTOR_CACHE",
                "SLATE_TPU_PEAKS", "SLATE_TPU_WARMUP",
                "SLATE_TPU_ARTIFACTS", "SLATE_TPU_METRICS",
                "SLATE_TPU_DEVMON"):
        tenv.pop(var, None)
    rc = subprocess.call(
        [sys.executable, "-m", "pytest", "tests/test_devmon.py", "-q",
         "-p", "no:cacheprovider", "-p", "no:xdist", "-p", "no:randomly"],
        env=tenv, cwd=here,
    )
    if rc != 0:
        return rc
    rc = subprocess.call(
        [sys.executable, "-c", _PALLAS_PANEL_DRIVER], env=tenv, cwd=here,
    )
    if rc != 0:
        print("perf gate: pallas interpret leg failed")
        return rc
    bench_diff = os.path.join("tools", "bench_diff.py")
    with tempfile.TemporaryDirectory(prefix="slate_perf_") as td:
        # leg 2a: the true trajectory pair must pass
        rc = subprocess.call(
            [sys.executable, bench_diff, "BENCH_r03.json",
             "BENCH_r04.json"], cwd=here,
        )
        if rc != 0:
            print("perf gate: true pair r03 -> r04 flagged a regression")
            return rc
        # leg 2b: a synthetic 2x GFLOP/s collapse must exit nonzero
        with open(os.path.join(here, "BENCH_r04.json")) as f:
            doc = json.load(f)
        doc = doc.get("parsed") if "parsed" in doc else doc
        if not isinstance(doc, dict) or "extra" not in doc:
            # same tolerance as bench_diff.load_bench: a re-recorded
            # raw-shape baseline or a died-sweep null payload is a
            # diagnosable gate failure, not a traceback
            print("perf gate: BENCH_r04.json carries no parsed payload")
            return 1
        if isinstance(doc.get("value"), (int, float)):
            doc["value"] *= 0.5
        for e in doc["extra"].values():
            if isinstance(e, dict) and "gflops" in e:
                e["gflops"] *= 0.5
        reg = os.path.join(td, "r04_regressed.json")
        with open(reg, "w") as f:
            json.dump(doc, f)
        rc = subprocess.call(
            [sys.executable, bench_diff, "BENCH_r04.json", reg], cwd=here,
        )
        if rc != 1:
            # rc must be THE regression verdict: 0 means the sentinel
            # missed, 2 means it never compared an entry (unusable
            # input) — either way the check proved nothing
            print(f"perf gate: synthetic regression not flagged (rc={rc})")
            return 1
        # leg 3: devmon serve stream + roofline classification, on the
        # scrubbed env (the driver and the report both resolve peaks)
        jsonl = os.path.join(td, "perf.jsonl")
        rc = subprocess.call(
            [sys.executable, "-c", _PERF_DRIVER],
            env=dict(tenv, SLATE_TPU_METRICS=jsonl, SLATE_TPU_DEVMON="1"),
            cwd=here,
        )
        if rc != 0:
            return rc
        rc = subprocess.call(
            [sys.executable, os.path.join("tools", "roofline_report.py"),
             jsonl],
            env=tenv, cwd=here,
        )
        if rc != 0:
            return rc
        # leg 4: quick warmed bench, floored against the checked-in
        # baseline (bench owns stdout for its JSON line)
        live = os.path.join(td, "bench_quick.json")
        with open(live, "w") as f:
            rc = subprocess.call(
                [sys.executable, "bench.py", "--quick"],
                env=tenv, cwd=here, stdout=f,
            )
        if rc != 0:
            return rc
        return subprocess.call(
            [sys.executable, bench_diff, "--floor",
             "BENCH_FLOOR_CPU.json", live],
            env=tenv, cwd=here,
        )


# Four-phase SDC drill for the --integrity gate.  SLATE_TPU_INTEGRITY
# ("full,abft") is read at import — the production activation path —
# and asserted; each phase then tunes an explicit policy (short
# quarantine cooldowns, hedging on/off) because the drill must finish
# in seconds.  Faults are armed POST-warmup (an sdc during warmup
# builds would be injected into discarded dummy dispatches, inflating
# the injected count the report joins against detections).
_INTEGRITY_DRIVER = """
import time
import jax
jax.config.update("jax_enable_x64", True)
import numpy as np
from slate_tpu.aux import faults, metrics
from slate_tpu.exceptions import SlateError
from slate_tpu.integrity import IntegrityPolicy, from_options
from slate_tpu.serve import buckets as bk
from slate_tpu.serve.cache import ExecutableCache
from slate_tpu.serve.factor_cache import FactorCache
from slate_tpu.serve.service import SolverService

p_env = from_options(None)
assert p_env is not None and p_env.mode == "full" and p_env.abft, (
    "SLATE_TPU_INTEGRITY must arm the plane")

n1, n2 = 12, 24

def prob(rt, n, seed):
    r = np.random.default_rng(seed)
    A = r.standard_normal((n, n))
    A = A @ A.T + n * np.eye(n) if rt == "posv" else A + n * np.eye(n)
    return rt, A, r.standard_normal((n, 2))

def run(svc, probs):
    futs = [svc.submit(rt, A, B) for rt, A, B in probs]
    ok = typed = wrong = 0
    for (rt, A, B), f in zip(probs, futs):
        try:
            X = f.result(timeout=300)
        except SlateError:
            typed += 1
            continue
        scale = np.abs(A).max() * np.abs(X).max() + np.abs(B).max()
        if np.abs(A @ X - B).max() <= 1e-6 * scale:
            ok += 1
        else:
            wrong += 1
    return ok, typed, wrong

def svc_for(pol, **kw):
    return SolverService(
        cache=ExecutableCache(manifest_path=None), batch_max=4,
        batch_window_s=0.002, dim_floor=16, nrhs_floor=4, replicas=2,
        integrity=pol, **kw)

# -- phase A: ABFT-certified stream under sdc_solve; hedged recovery --
pol = IntegrityPolicy(mode="full", abft=True, hedge_factor=0.0,
                      quarantine_cooldown_s=0.25)
svc = svc_for(pol)
for rt, n in (("gesv", n1), ("posv", n2)):
    k = bk.bucket_for(rt, n, n, 2, np.float64, floor=16, nrhs_floor=4,
                      tag="abft")
    svc.cache.ensure_manifest(k, (1, 4))
svc.warmup()
faults.configure("sdc_solve:every=4,seed=2")
faults.on()
probs = [prob("gesv", n1, i) for i in range(24)] + [
    prob("posv", n2, 100 + i) for i in range(12)]
ok, typed, wrong = run(svc, probs)
faults.reset()
assert wrong == 0, f"phase A: {wrong} silent wrong answers delivered"
assert ok + typed == len(probs) and ok >= 30, (ok, typed)
c = metrics.counters()
assert c.get("serve.integrity.fail", 0) >= 1, c
assert c.get("serve.integrity.recovered", 0) >= 1, c
assert c.get("serve.hedge.sent", 0) >= 1, c
assert c.get("serve.hedge.won", 0) >= 1, c
nA = len(probs)

# -- phase B: every dispatch corrupted -> quarantine, then probe back --
faults.configure("sdc_solve:every=1")
faults.on()
okB, typedB, wrongB = run(svc, [prob("gesv", n1, 500 + i)
                                for i in range(8)])
faults.reset()
assert wrongB == 0 and okB + typedB == 8, (okB, typedB, wrongB)
assert metrics.counters().get("serve.integrity.quarantined", 0) >= 1, (
    "poisoned replicas never quarantined")
time.sleep(0.3)  # past the quarantine cooldown: next delivery probes
okP, typedP, wrongP = run(svc, [prob("gesv", n1, 600 + i)
                                for i in range(6)])
assert wrongP == 0 and okP == 6, (okP, typedP, wrongP)
h = svc.health()
assert h["integrity"] is not None and not h["integrity"]["quarantined"], (
    h["integrity"])
assert metrics.counters().get("serve.integrity.unquarantined", 0) >= 1
svc.stop()

# -- phase C: sdc_factor through the factor-cache miss path -----------
pol2 = IntegrityPolicy(mode="full", hedge_factor=0.0,
                       quarantine_cooldown_s=0.25)
svc2 = svc_for(pol2, factor_cache=FactorCache())
faults.configure("sdc_factor:every=3,seed=1")
faults.on()
probsC = [prob("gesv", n1, 700 + i) for i in range(10)] + [
    prob("posv", n2, 800 + i) for i in range(4)]
okC, typedC, wrongC = run(svc2, probsC)
# repeated-A hits against possibly-poisoned cached factors: the
# residual fence must catch them (counted stale), never a wrong X
rt0, A0, _ = prob("gesv", n1, 700)
okR, typedR, wrongR = run(svc2, [
    (rt0, A0, np.random.default_rng(900 + i).standard_normal((n1, 2)))
    for i in range(4)])
faults.reset()
assert wrongC == 0 and wrongR == 0, (wrongC, wrongR)
assert okC + typedC == len(probsC) and okR + typedR == 4
svc2.stop()
nC = len(probsC) + 4

# -- phase D: stragglers hedge off a deliberately-slowed lane ---------
pol3 = IntegrityPolicy(mode="full", hedge_factor=0.5,
                       hedge_min_age_s=0.005)
svc3 = svc_for(pol3)
# nrhs=5 -> rhs bucket 8: a FRESH bucket label, so the p99 history the
# straggler trigger reads comes from phase D's own warmed clean
# traffic (phase C's unwarmed first dispatch put its compile wall into
# the 16x16x4 label's histogram, which would stretch p99 to seconds)
def probD(seed):
    r = np.random.default_rng(seed)
    return ("gesv", r.standard_normal((n1, n1)) + n1 * np.eye(n1),
            r.standard_normal((n1, 5)))
kD = bk.bucket_for("gesv", n1, n1, 5, np.float64, floor=16, nrhs_floor=4)
svc3.cache.ensure_manifest(kD, (1, 4))
svc3.warmup()
# clean traffic first: the straggler trigger compares queued age to
# the bucket's OWN p99 history
okW, _, _ = run(svc3, [probD(950 + i) for i in range(6)])
assert okW == 6
sent0 = metrics.counters().get("serve.hedge.sent", 0)
won0 = metrics.counters().get("serve.hedge.won", 0)
wasted0 = metrics.counters().get("serve.hedge.wasted", 0)
faults.configure("latency:every=2,ms=150")  # every other dispatch slow
faults.on()
okD, typedD, wrongD = run(svc3, [probD(1000 + i) for i in range(32)])
faults.reset()
assert wrongD == 0 and okD == 32, (okD, typedD, wrongD)
# drain before reading: the losing twins of already-resolved futures
# are still queued/in flight, and their wasted/won accounting lands at
# their own completion (stop(drain=True) is the satellite doing real
# work here)
svc3.stop(drain=True, drain_timeout=60.0)
c = metrics.counters()
sent1 = c.get("serve.hedge.sent", 0)
assert sent1 > sent0, "no straggler was hedged off the slowed lane"
assert (c.get("serve.hedge.won", 0) - won0
        + c.get("serve.hedge.wasted", 0) - wasted0) >= 1, (
    "hedged pairs completed without won/wasted accounting")
total = nA + 8 + 6 + nC + 6 + 32
print(f"integrity driver: {total} requests over 4 phases, 0 silent "
      f"wrong answers; fail={int(c.get('serve.integrity.fail', 0))} "
      f"recovered={int(c.get('serve.integrity.recovered', 0))} "
      f"hedge sent={int(c.get('serve.hedge.sent', 0))} "
      f"won={int(c.get('serve.hedge.won', 0))} "
      f"quarantined={int(c.get('serve.integrity.quarantined', 0))} "
      f"unquarantined={int(c.get('serve.integrity.unquarantined', 0))}")
"""

# Negative leg: the SAME corruption with the plane disabled must
# deliver wrong answers (proving the injection is real) and the report
# over its JSONL must exit NONZERO (proving an escape is flagged).
_INTEGRITY_ESCAPE_DRIVER = """
import jax
jax.config.update("jax_enable_x64", True)
import numpy as np
from slate_tpu.aux import faults
from slate_tpu.serve.cache import ExecutableCache
from slate_tpu.serve.service import SolverService

svc = SolverService(cache=ExecutableCache(manifest_path=None),
                    batch_max=4, batch_window_s=0.002, dim_floor=16,
                    nrhs_floor=4, integrity=False)
assert svc._integrity is None
n = 12
rng = np.random.default_rng(0)
svc.submit("gesv", rng.standard_normal((n, n)) + n * np.eye(n),
           rng.standard_normal((n, 2))).result(timeout=300)  # warm
faults.configure("sdc_solve:every=2,seed=0")
faults.on()
wrong = 0
for i in range(8):
    r = np.random.default_rng(10 + i)
    A = r.standard_normal((n, n)) + n * np.eye(n)
    B = r.standard_normal((n, 2))
    X = svc.submit("gesv", A, B).result(timeout=300)
    scale = np.abs(A).max() * np.abs(X).max() + np.abs(B).max()
    if np.abs(A @ X - B).max() > 1e-6 * scale:
        wrong += 1
faults.reset()
svc.stop()
assert wrong >= 1, "undefended stream delivered no wrong X (site dead?)"
print(f"escape driver: {wrong} silent wrong answers delivered "
      "(integrity off, as designed)")
"""


def integrity_gate() -> int:
    """Integrity gate, three legs: (1) the integrity suite (ABFT
    checks, certification, quarantine, hedging, drain/restore-stuck
    satellites); (2) the four-phase SDC drill — sdc_factor + sdc_solve
    armed over a warmed mixed gesv/posv stream with zero silent wrong
    answers, quarantine engage/recover, hedges sent and won — judged
    by tools/integrity_report.py (exit 0); (3) the escape proof: the
    same corruption with the plane OFF delivers wrong answers and the
    report exits NONZERO on that JSONL."""
    import tempfile

    here = os.path.dirname(os.path.abspath(__file__)) or "."
    rc = subprocess.call(
        [sys.executable, "-m", "pytest", "tests/test_integrity.py", "-q",
         "-p", "no:cacheprovider", "-p", "no:xdist", "-p", "no:randomly"],
        env=dict(os.environ, JAX_PLATFORMS="cpu"), cwd=here,
    )
    if rc != 0:
        return rc
    with tempfile.TemporaryDirectory(prefix="slate_integrity_") as td:
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        for var in ("SLATE_TPU_FAULTS", "SLATE_TPU_FACTOR_CACHE",
                    "SLATE_TPU_TENANTS", "SLATE_TPU_ADAPTIVE",
                    "SLATE_TPU_INTEGRITY", "SLATE_TPU_WARMUP",
                    "SLATE_TPU_ARTIFACTS"):
            env.pop(var, None)
        jsonl = os.path.join(td, "integrity.jsonl")
        rc = subprocess.call(
            [sys.executable, "-c", _INTEGRITY_DRIVER],
            env=dict(env, SLATE_TPU_METRICS=jsonl,
                     SLATE_TPU_INTEGRITY="full,abft"),
            cwd=here,
        )
        if rc != 0:
            return rc
        rc = subprocess.call(
            [sys.executable, os.path.join("tools", "integrity_report.py"),
             jsonl],
            cwd=here,
        )
        if rc != 0:
            return rc
        # escape leg: plane off, same sites armed — the report MUST
        # flag the run (a verdict tool that cannot fail proves nothing)
        esc = os.path.join(td, "escape.jsonl")
        rc = subprocess.call(
            [sys.executable, "-c", _INTEGRITY_ESCAPE_DRIVER],
            env=dict(env, SLATE_TPU_METRICS=esc), cwd=here,
        )
        if rc != 0:
            return rc
        rc = subprocess.call(
            [sys.executable, os.path.join("tools", "integrity_report.py"),
             esc],
            cwd=here,
        )
        if rc == 0:
            print("integrity gate: report failed to flag an undefended "
                  "SDC escape")
            return 1
    return 0


# Race-plane drivers for the --race gate.  The stress leg runs the
# chaos/hedge/drain/quarantine paths under the INSTRUMENTED sync
# runtime (SLATE_TPU_SYNC_CHECK env — the production activation path,
# read at import before any lock is constructed) with seeded yield
# points, then dumps the runtime's findings for tools/race_report.py
# to judge: the shipped tree must come out clean.
_RACE_STRESS_DRIVER = """
import sys
import time
import jax
jax.config.update("jax_enable_x64", True)
import numpy as np
from slate_tpu.aux import faults, sync
from slate_tpu.exceptions import SlateError
from slate_tpu.integrity import IntegrityPolicy
from slate_tpu.serve import buckets as bk
from slate_tpu.serve.cache import ExecutableCache
from slate_tpu.serve.service import SolverService

out = sys.argv[1]
assert sync.is_on(), "SLATE_TPU_SYNC_CHECK must arm the runtime"
from slate_tpu.aux import metrics
assert metrics.is_on(), "stress leg needs metrics (the hedge p99 source)"
pol = IntegrityPolicy(mode="full", hedge_factor=0.5, hedge_min_age_s=0.005,
                      quarantine_cooldown_s=0.2)
svc = SolverService(cache=ExecutableCache(manifest_path=None), batch_max=4,
                    batch_window_s=0.002, dim_floor=16, nrhs_floor=4,
                    replicas=2, integrity=pol, retry_backoff_s=0.002,
                    breaker_cooldown_s=0.02, retry_seed=0)
n = 12
k = bk.bucket_for("gesv", n, n, 2, np.float64, floor=16, nrhs_floor=4)
svc.cache.ensure_manifest(k, (1, 4))
svc.warmup()

def prob(seed):
    r = np.random.default_rng(seed)
    return r.standard_normal((n, n)) + n * np.eye(n), r.standard_normal((n, 2))

# clean warmed traffic first: the straggler sweep compares queued age
# to the bucket's OWN p99 history
futs = [svc.submit("gesv", *prob(i)) for i in range(8)]
for f in futs:
    assert np.all(np.isfinite(f.result(timeout=300)))
# chaos phase: injected latency makes stragglers (hedge clones share
# futures across lanes), sdc_solve drives certificate re-execution and
# quarantine churn, worker_death exercises supervision re-enqueues,
# lock_contend inflates instrumented hold times — the concurrency
# paths PR14's review passes kept catching bugs in, now swept by the
# lockset/lock-order checkers under seeded yields
faults.configure(
    "latency:every=3,ms=40;sdc_solve:every=5,seed=1;"
    "worker_death:every=11;lock_contend:p=0.05,seed=2,ms=1")
faults.on()
ok = typed = 0
futs = [svc.submit("gesv", *prob(100 + i), retries=2) for i in range(32)]
for f in futs:
    try:
        assert np.all(np.isfinite(f.result(timeout=300)))
        ok += 1
    except SlateError:
        typed += 1
faults.reset()
assert ok + typed == 32, "a future hung"
# hedge-pressure rounds: the chaos phase above does not GUARANTEE a
# straggler hedge (timing-dependent), and a leg advertised as sweeping
# the hedge path must not pass without it — inflate every dispatch so
# the backlog ages past hedge_factor x p99 until the _HedgeGroup
# probes actually fire, bounded
rounds = 0
while "_HedgeGroup.delivered" not in sync.report()["field_names"]:
    rounds += 1
    assert rounds <= 5, (
        "hedge path never exercised: " + str(sync.report()["field_names"]))
    faults.configure("latency:every=1,ms=50")
    faults.on()
    futs = [svc.submit("gesv", *prob(1000 * rounds + i)) for i in range(16)]
    for f in futs:
        try:
            f.result(timeout=300)
        except SlateError:
            pass
    faults.reset()
svc.stop(drain=True, drain_timeout=60.0)
rep = sync.report()
sync.dump(out)
# coverage, not just a count: the worker-pool, hedge-group and
# factor-cache probes are distinct bug surfaces (PR14's fixes were on
# the hedge path) — a fields total alone cannot tell them apart
names = set(rep["field_names"])
assert {"_Replica.q", "_Replica.inflight"} <= names, names
assert "_HedgeGroup.delivered" in names, names
print(f"race stress driver: {ok} delivered / {typed} typed under the "
      f"instrumented runtime (+{rounds} hedge round(s)); "
      f"{rep['fields']} probed fields, "
      f"{len(rep['edges'])} runtime order edges, "
      f"{len(rep['violations'])} violations")
"""

# Planted lock-order inversion: two locks, two threads, inverted
# acquisition order (sequenced, so the fixture detects without
# deadlocking).  The detector must report the inversion with BOTH
# stacks, and race_report over the dump must exit NONZERO.
_RACE_INVERSION_DRIVER = """
import sys
import threading
from slate_tpu.aux import sync

out = sys.argv[1]
assert sync.is_on(), "SLATE_TPU_SYNC_CHECK must arm the runtime"
A = sync.Lock(name="fixture.A")
B = sync.Lock(name="fixture.B")

def t1():
    with A:
        with B:
            pass

def t2():
    with B:
        with A:
            pass

th = threading.Thread(target=t1); th.start(); th.join()  # records A -> B
th = threading.Thread(target=t2); th.start(); th.join()  # inverts: B -> A
sync.dump(out)
v = [x for x in sync.violations() if x["kind"] == "lock_order"]
assert v and len(v[0]["stacks"]) == 2 and all(v[0]["stacks"]), v
print("race inversion driver: planted inversion detected, both stacks")
"""

# Planted unguarded write: a shared field probed by guarded() touched
# by two threads with no common lock and no happens-before edge.  The
# lockset checker must flag it, and race_report must exit NONZERO.
_RACE_UNGUARDED_DRIVER = """
import sys
import threading
from slate_tpu.aux import sync

out = sys.argv[1]
assert sync.is_on(), "SLATE_TPU_SYNC_CHECK must arm the runtime"

class Shared:
    def __init__(self):
        self.hits = 0  # guarded by: lock — and the writes below skip it

s = Shared()

def writer():
    sync.guarded(s, "hits")
    s.hits += 1

th = threading.Thread(target=writer); th.start(); th.join()
sync.guarded(s, "hits")  # main thread: no lock, no hand-off edge
s.hits += 1
sync.dump(out)
v = [x for x in sync.violations() if x["kind"] == "lockset"]
assert v and len(v[0]["stacks"]) == 2, v
print("race unguarded driver: planted unguarded write detected")
"""


def race_gate() -> int:
    """Race/deadlock gate, five legs:

    1. the race suite (static rule fixtures, the deterministic
       deadlock-reproduction and Condition hand-off regression tests);
    2. the static rules over the full tree (lock-discipline +
       race-guarded-by + race-lock-order) via the slate-lint CLI;
    3. the lock-order graph artifact check (cycle-free AND in sync
       with the checked-in LOCK_ORDER.json);
    4. the instrumented chaos/hedge/drain/quarantine stress leg under
       SLATE_TPU_SYNC_CHECK with seeded yields, judged clean by
       tools/race_report.py;
    5. the two planted fixtures (lock-order inversion, unguarded
       annotated write) — race_report must exit NONZERO on each (a
       verdict tool that cannot fail proves nothing)."""
    import tempfile

    here = os.path.dirname(os.path.abspath(__file__)) or "."
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    for var in ("SLATE_TPU_FAULTS", "SLATE_TPU_TENANTS",
                "SLATE_TPU_ADAPTIVE", "SLATE_TPU_FACTOR_CACHE",
                "SLATE_TPU_INTEGRITY", "SLATE_TPU_SYNC_CHECK",
                "SLATE_TPU_WARMUP", "SLATE_TPU_ARTIFACTS",
                "SLATE_TPU_METRICS"):
        env.pop(var, None)
    rc = subprocess.call(
        [sys.executable, "-m", "pytest", "tests/test_races.py", "-q",
         "-p", "no:cacheprovider", "-p", "no:xdist", "-p", "no:randomly"],
        env=env, cwd=here,
    )
    if rc != 0:
        return rc
    rc = subprocess.call(
        [sys.executable, os.path.join("tools", "slate_lint.py"),
         "--rules", "lock-discipline,race-guarded-by,race-lock-order"],
        env=env, cwd=here,
    )
    if rc != 0:
        print("race gate: static race rules flagged the tree")
        return rc
    rc = subprocess.call(
        [sys.executable, os.path.join("tools", "race_report.py"),
         "--check-graph"],
        env=env, cwd=here,
    )
    if rc != 0:
        print("race gate: lock-order graph artifact out of sync")
        return rc
    with tempfile.TemporaryDirectory(prefix="slate_race_") as td:
        legs = (
            ("stress", _RACE_STRESS_DRIVER,
             "1,seed=7,yield=0.2,yield_us=200", True),
            ("inversion", _RACE_INVERSION_DRIVER, "1,seed=7", False),
            ("unguarded", _RACE_UNGUARDED_DRIVER, "1,seed=7", False),
        )
        for name, driver, spec, expect_clean in legs:
            dump = os.path.join(td, f"{name}.json")
            leg_env = dict(env, SLATE_TPU_SYNC_CHECK=spec)
            if name == "stress":
                # straggler hedging needs the p99 source: metrics on
                # (the sink file is scratch — race_report judges the
                # sync dump, not the JSONL)
                leg_env["SLATE_TPU_METRICS"] = os.path.join(
                    td, "stress_metrics.jsonl")
            rc = subprocess.call(
                [sys.executable, "-c", driver, dump],
                env=leg_env, cwd=here,
            )
            if rc != 0:
                print(f"race gate: {name} driver failed (rc={rc})")
                return rc
            rc = subprocess.call(
                [sys.executable, os.path.join("tools", "race_report.py"),
                 dump],
                cwd=here,
            )
            if expect_clean and rc != 0:
                print(f"race gate: {name} leg reported violations on "
                      "the shipped tree")
                return rc
            if not expect_clean and rc == 0:
                print(f"race gate: report failed to flag the planted "
                      f"{name} fixture")
                return 1
    return 0


# the full-tree slate-lint run must stay cheap enough to gate every PR
# on the 2-core CI box; blowing this budget is itself a gate failure
LINT_BUDGET_S = 15.0


def lint_gate() -> int:
    """Static-analysis gate (slate_tpu/analysis + tools/slate_lint.py):

    1. the lint test suite — per-rule fixture positives/negatives,
       suppression + baseline semantics, JSON schema, and a self-run
       asserting the shipped tree is clean;
    2. a full-tree slate-lint run against the checked-in baseline —
       nonzero on any NEW finding, and nonzero if the run blows the
       :data:`LINT_BUDGET_S` runtime budget.
    """
    here = os.path.dirname(os.path.abspath(__file__)) or "."
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    rc = subprocess.call(
        [sys.executable, "-m", "pytest", "tests/test_lint.py", "-q",
         "-p", "no:cacheprovider"],
        env=env, cwd=here,
    )
    if rc != 0:
        print("lint: fixture/self-run suite failed")
        return rc
    # the CLI, not an in-process import: tools/slate_lint.py loads the
    # analysis package without executing slate_tpu/__init__, so this
    # gate keeps reporting parse errors as findings even when the tree
    # is import-broken.  Wall clock (interpreter startup included) is
    # what the budget means on the CI box.
    t0 = time.monotonic()
    rc = subprocess.call(
        [sys.executable, os.path.join("tools", "slate_lint.py")],
        env=env, cwd=here,
    )
    wall = time.monotonic() - t0
    if wall > LINT_BUDGET_S:
        print(f"lint: full-tree run took {wall:.1f}s, over the "
              f"{LINT_BUDGET_S:.0f}s per-PR budget")
        return 1
    if rc != 0:
        print("lint: new findings (fix them, suppress with a "
              "justification, or --write-baseline for accepted legacy)")
        return rc
    print(f"lint: tree clean ({wall:.1f}s)")
    return 0


# Soak driver: ~10^4 requests (x100 with SLATE_SOAK_SCALE=full)
# replayed open-loop against ONE service with EVERY plane armed at
# once — batching, factor cache, tenants+adaptive admission, deadline
# traffic, integrity certification with hedging and quarantine — while
# latency/SDC/worker-death faults fire and the health timeline
# samples.  Phase 2 is the record->replay round trip: a low-rate
# stream is recorded off the live delivery tap, the RECORDING is
# replayed twice (same spec, same seed), and the driver asserts the
# workload-mix histograms agree and the two runs land within the
# documented tolerance.  tools/soak_report.py judges the dump.
_SOAK_DRIVER = """
import os
import sys
import jax
jax.config.update("jax_enable_x64", True)
import numpy as np
from slate_tpu.aux import faults, metrics, spans
from slate_tpu.integrity import policy as ipol
from slate_tpu.serve import buckets as bk
from slate_tpu.serve.cache import ExecutableCache
from slate_tpu.serve.factor_cache import FactorCache
from slate_tpu.serve.service import SolverService
from slate_tpu.soak import record, replay
from slate_tpu.soak.timeline import TimelineSampler

full = os.environ.get("SLATE_SOAK_SCALE") == "full"
S = 100 if full else 1
metrics.on()
metrics.reset()
spans.on(ring=262144 if full else 65536)
svc = SolverService(
    cache=ExecutableCache(manifest_path=None), batch_max=8,
    batch_window_s=0.001, dim_floor=16, nrhs_floor=4, replicas=2,
    retry_backoff_s=0.002, breaker_cooldown_s=0.02, retry_seed=0,
    factor_cache=FactorCache(max_entries=64),
    tenants="gold:weight=4;good:weight=2;free:rate=300,share=0.5;"
            "abuser:rate=60,burst=16,share=0.25",
    adaptive=True, latency_budget_s=0.5,
    integrity=ipol.parse_spec("full,hedge=1.5,cooldown=0.25"),
)
for rt, n in (("gesv", 12), ("posv", 12), ("gesv", 24)):
    k = bk.bucket_for(rt, n, n, 2, np.float64, floor=16, nrhs_floor=4)
    svc.cache.ensure_manifest(k, (1, 8))
    # the factor cache dispatches hits onto the solve-phase sibling:
    # omit it from warmup and the soak compiles mid-run
    svc.cache.ensure_manifest(k.solve_sibling(), (1, 8))
svc.warmup()

spec = replay.merge_specs(
    replay.gen_repeated_a(5000 * S, seed=2, rate_rps=240, distinct=10),
    replay.gen_repeated_a(1500 * S, seed=3, rate_rps=75, distinct=4,
                          routine="posv"),
    replay.gen_multitenant(1800 * S, seed=1, rate_rps=88),
    replay.gen_deadline_storm(800 * S, seed=4, rate_rps=40),
    replay.gen_adversarial_flood(900 * S, seed=5, rate_rps=45),
)
rt_spec = replay.merge_specs(
    replay.gen_multitenant(700, seed=11, rate_rps=70),
    replay.gen_repeated_a(500, seed=12, rate_rps=60, distinct=5),
)
# pool-warm BOTH phases' factors, then zero the books: the soak
# measures the steady state (0 compiles, warm factor cache)
replay.replay(svc, replay.warm_spec(spec), speed=1.0, seed=0)
replay.replay(svc, replay.warm_spec(rt_spec), speed=1.0, seed=0)
metrics.reset()

faults.configure("latency:every=97,ms=30;sdc_solve:every=211,seed=3;"
                 "worker_death:every=1501")
faults.on()
sampler = TimelineSampler(svc, period_s=0.05).start()
res = replay.replay(svc, spec, speed=1.0, seed=0)
faults.reset()
assert res["submitted"] == (res["delivered"] + res["typed_errors"]
                            + res["refused"]), res
print(f"soak main: {res['submitted']} submitted, "
      f"{res['delivered']} delivered, {res['typed_errors']} typed, "
      f"{res['refused']} refused, {res['bad_results']} bad, "
      f"{res['requests_per_s']} req/s, "
      f"p99={(res['p99_s'] or 0) * 1e3:.1f}ms")

# ---- elastic lifecycle under the instrumented sync runtime ---------
# grow the fleet by one lane (phase 2 traffic rides on 3 replicas),
# shrink it back after the determinism runs: the add/remove paths run
# inside the same SLATE_TPU_SYNC_CHECK net as the rest of the drill
added = svc.add_replica()
with svc._cond:
    fleet = len(svc._replicas)
assert fleet == 3, fleet
print(f"soak: replica {added} added, fleet={fleet}")

# ---- phase 2: record -> replay round trip + determinism ------------
rec = record.Recorder().attach()
rt_res = replay.replay(svc, rt_spec, speed=1.0, seed=0)
rec.detach()
recorded = rec.rows()
assert len(recorded) == rt_res["delivered"] + rt_res["typed_errors"], (
    len(recorded), rt_res)
mix_in = record.mix_histogram(recorded)

runs = []
for i in (0, 1):
    r2 = record.Recorder().attach()
    runs.append((replay.replay(svc, recorded, speed=1.0, seed=0),
                 record.mix_histogram(r2.detach().rows())))
mix_out = runs[0][1]

def close(a, b, what):
    assert set(a) == set(b), (what, sorted(a), sorted(b))
    for key in a:
        tol = max(5, int(0.05 * a[key]))
        assert abs(a[key] - b[key]) <= tol, (what, key, a[key], b[key])

close(mix_in["tenants"], mix_out["tenants"], "tenants")
close(mix_in["priorities"], mix_out["priorities"], "priorities")
close(mix_in["shapes"], mix_out["shapes"], "shapes")
# repeat groups: fingerprints are of the matrix BYTES, which differ
# between original and regenerated operands — the preserved invariant
# is the group-size structure, not the fingerprint values
gs_in = sorted(mix_in["repeat_groups"].values())
gs_out = sorted(mix_out["repeat_groups"].values())
assert abs(len(gs_in) - len(gs_out)) <= 1, (gs_in, gs_out)
assert abs(sum(gs_in) - sum(gs_out)) <= max(10, int(0.05 * sum(gs_in)))
# determinism: same recorded spec + same seed, twice — delivered
# tallies agree within the documented tolerance (scheduling jitter
# moves a few requests between delivered and shed, never the sum)
(ra, _), (rb, _) = runs
for r in (ra, rb):
    assert r["submitted"] == (r["delivered"] + r["typed_errors"]
                              + r["refused"]), r
tol = max(10, int(0.02 * ra["submitted"]))
assert abs(ra["delivered"] - rb["delivered"]) <= tol, (ra, rb)
print(f"round trip: {len(recorded)} recorded, mixes agree; "
      f"determinism: {ra['delivered']} vs {rb['delivered']} delivered")

# drain the added lane back out mid-traffic-history: every queued
# request it held must re-home (none dropped — the books below still
# reconcile) and health must show the lane as a terminal row
removed = svc.remove_replica(added, drain_timeout=120)
h = svc.health()
states = {l["name"]: l.get("state") for l in h["replicas"]}
assert states.get(removed) == "removed", states
assert removed in (h["capacity"] or {}).get("terminal_lanes", [removed]), h
with svc._cond:
    fleet = len(svc._replicas)
assert fleet == 2, fleet
print(f"soak: replica {removed} drained + removed, fleet={fleet}")

pressure = spans.pressure()
if pressure["evicted"] == 0:
    replay.orphan_spans()  # publishes the soak.orphan_spans gauge
else:  # an evicting ring fabricates orphans; report skips the check
    print(f"span ring evicted {pressure['evicted']} - orphan audit "
          "skipped")
sampler.stop()
svc.stop(drain=True, drain_timeout=300)
c = metrics.counters()
assert c["serve.requests"] == c["soak.submitted"] - c["soak.refused"], (
    c["serve.requests"], c["soak.submitted"], c["soak.refused"])
# the gate armed SLATE_TPU_SYNC_CHECK: the whole drill (replica
# add/remove included) ran under the lockset/inversion checker, and a
# single recorded violation fails the soak right here
from slate_tpu.aux import sync
assert sync.is_on(), "SLATE_TPU_SYNC_CHECK must arm the runtime"
v = sync.violations()
assert not v, ("sync checker flagged the drill", v[:3])
metrics.dump()
print("soak driver: all phases complete, books reconcile, sync clean")
"""

# Negative leg: the SAME SDC corruption with the integrity plane AND
# the factor-cache residual fence disarmed must deliver wrong answers
# to the replay engine's client-side check (soak.bad_results > 0) and
# the soak report over that JSONL must exit NONZERO.
_SOAK_ESCAPE_DRIVER = """
import jax
jax.config.update("jax_enable_x64", True)
import numpy as np
from slate_tpu.aux import faults, metrics, spans
from slate_tpu.serve import buckets as bk
from slate_tpu.serve.cache import ExecutableCache
from slate_tpu.serve.service import SolverService
from slate_tpu.soak import replay
from slate_tpu.soak.timeline import TimelineSampler

metrics.on()
metrics.reset()
spans.on(ring=8192)
svc = SolverService(cache=ExecutableCache(manifest_path=None),
                    batch_max=8, batch_window_s=0.001, dim_floor=16,
                    nrhs_floor=4, replicas=2, factor_cache=False,
                    integrity=False)
assert svc._integrity is None
k = bk.bucket_for("gesv", 12, 12, 2, np.float64, floor=16, nrhs_floor=4)
svc.cache.ensure_manifest(k, (1, 8))
svc.warmup()
metrics.reset()
spec = replay.gen_repeated_a(400, seed=7, rate_rps=200, distinct=4)
faults.configure("sdc_solve:every=7,seed=5")
faults.on()
sampler = TimelineSampler(svc, period_s=0.05).start()
res = replay.replay(svc, spec, speed=1.0, seed=0)
faults.reset()
sampler.stop()
replay.orphan_spans()  # publishes the soak.orphan_spans gauge
svc.stop(drain=True, drain_timeout=120)
metrics.dump()
assert res["bad_results"] > 0, (
    "undefended soak delivered no wrong X (site dead?)", res)
print(f"escape driver: {res['bad_results']} silent wrong answers "
      "delivered (integrity off, as designed)")
"""


def soak_gate(full: bool = False) -> int:
    """Trace-driven soak gate, three legs: (1) the soak suite
    (recorder/replay/timeline units, all-planes health shape,
    metrics_merge); (2) the soak drill — ~10^4 requests (~10^6 with
    ``--full``) against a fully-armed 2-replica service under
    latency/SDC/worker-death faults, with the record->replay round
    trip and the two-run determinism check inline — judged by
    tools/soak_report.py (exit 0: books reconcile, zero escapes, zero
    orphans, tails in budget, compile-free steady state, every
    disruption recovered); (3) the escape proof: the same SDC with
    every defense disarmed must make the report exit NONZERO."""
    import tempfile

    here = os.path.dirname(os.path.abspath(__file__)) or "."
    rc = subprocess.call(
        [sys.executable, "-m", "pytest", "tests/test_soak.py", "-q",
         "-p", "no:cacheprovider", "-p", "no:xdist", "-p", "no:randomly"],
        env=dict(os.environ, JAX_PLATFORMS="cpu"), cwd=here,
    )
    if rc != 0:
        return rc
    with tempfile.TemporaryDirectory(prefix="slate_soak_") as td:
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        for var in ("SLATE_TPU_FAULTS", "SLATE_TPU_FACTOR_CACHE",
                    "SLATE_TPU_TENANTS", "SLATE_TPU_ADAPTIVE",
                    "SLATE_TPU_INTEGRITY", "SLATE_TPU_WARMUP",
                    "SLATE_TPU_ARTIFACTS"):
            env.pop(var, None)
        jsonl = os.path.join(td, "soak.jsonl")
        # the drill runs under the instrumented sync runtime: every
        # lock acquisition in the replay (including the add/remove
        # replica lifecycle it now exercises) is order-checked against
        # LOCK_ORDER.json, so a lock-order regression fails the soak
        # even before the race gate runs
        denv = dict(env, SLATE_TPU_METRICS=jsonl,
                    SLATE_TPU_SYNC_CHECK="1")
        if full:
            denv["SLATE_SOAK_SCALE"] = "full"
        rc = subprocess.call(
            [sys.executable, "-c", _SOAK_DRIVER], env=denv, cwd=here,
        )
        if rc != 0:
            return rc
        rc = subprocess.call(
            [sys.executable, os.path.join("tools", "soak_report.py"),
             jsonl, "--p99-budget-ms", "2000",
             "--tenant-p99-budget-ms", "2000",
             "--min-timeline-rows", "50",
             "--min-delivered", str(500000 if full else 5000)],
            cwd=here,
        )
        if rc != 0:
            return rc
        # escape leg: defenses off, same SDC — the report MUST flag
        # the run (a verdict tool that cannot fail proves nothing).
        # "defenses off" means the DELIVERY defenses: the instrumented
        # sync runtime stays armed so a lock-order regression on the
        # escape path cannot hide behind the expected nonzero verdict
        esc = os.path.join(td, "escape.jsonl")
        rc = subprocess.call(
            [sys.executable, "-c", _SOAK_ESCAPE_DRIVER],
            env=dict(env, SLATE_TPU_METRICS=esc,
                     SLATE_TPU_SYNC_CHECK="1"),
            cwd=here,
        )
        if rc != 0:
            return rc
        rc = subprocess.call(
            [sys.executable, os.path.join("tools", "soak_report.py"), esc],
            cwd=here,
        )
        if rc == 0:
            print("soak gate: report failed to flag an undefended "
                  "SDC escape")
            return 1
    return 0


# Elastic-capacity driver: one recorded bursty trace (gen_burst ->
# record.save -> record.load, so the measured workload IS a spec file)
# replayed twice under a fixed per-dispatch latency tax that saturates
# a single lane at ~60 req/s.  Leg 1: a static replicas=1 fleet eats
# the 120 req/s burst and blows its tail budget.  Leg 2: the SAME
# trace with SLATE_TPU_SCALE armed — the autoscaler must grow the
# fleet through the burst (artifact-warmed lanes, zero compiles),
# hold the budget, and give every lane back.  The driver only
# publishes the evidence (scale.gate.* gauges + the decision
# timeline); tools/capacity_report.py renders the verdict.
_SCALE_DRIVER = """
import os
import sys
import threading
import time
import jax
jax.config.update("jax_enable_x64", True)
import numpy as np
from slate_tpu.aux import faults, metrics, spans
from slate_tpu.serve import buckets as bk
from slate_tpu.scale import gate
from slate_tpu.serve.cache import ExecutableCache
from slate_tpu.serve.factor_cache import FactorCache
from slate_tpu.serve.service import SolverService
from slate_tpu.soak import record, replay

art, trace = sys.argv[1], sys.argv[2]
BUDGET_S = 1.0
POLICY = ("min=1,max=3,up=1.0,down=0.2,up_cooldown=0.25,"
          "down_cooldown=2.0,step=2,period=0.05")

metrics.on()
metrics.reset()
spans.on(ring=65536)

spec = replay.gen_burst(500, seed=9, base_rps=30, burst_rps=120,
                        burst_start_s=1.0, burst_len_s=2.0,
                        n=12, nrhs=2, distinct=4)
record.save(spec, trace, source="gen_burst")
rows = record.load(trace)

def build():
    svc = SolverService(
        cache=ExecutableCache(manifest_path=None, artifact_dir=art),
        batch_max=1, batch_window_s=0.0005, dim_floor=16,
        nrhs_floor=4, replicas=1,
        factor_cache=FactorCache(max_entries=16),
    )
    k = bk.bucket_for("gesv", 12, 12, 2, np.float64, floor=16,
                      nrhs_floor=4)
    svc.cache.ensure_manifest(k, (1,))
    svc.cache.ensure_manifest(k.solve_sibling(), (1,))
    svc.warmup()
    # factor-pool warm with the replay's seed: the measured legs hit
    replay.replay(svc, replay.warm_spec(rows), speed=1.0, seed=0)
    return svc

# fixed latency tax on every dispatch: capacity is lanes, not luck
faults.configure("latency:every=1,ms=12")

# ---- leg 1: static fleet (replicas=1, scaler unarmed) --------------
os.environ.pop("SLATE_TPU_SCALE", None)
svc = build()
assert svc._scaler is None, "scaler armed without SLATE_TPU_SCALE"
faults.on()
res_static = replay.replay(svc, rows, speed=1.0, seed=0)
faults.off()  # off, not reset: leg 2 re-arms the SAME latency tax
svc.stop(drain=True, drain_timeout=120)
print(f"static leg: p99={(res_static['p99_s'] or 0) * 1e3:.1f}ms "
      f"over {res_static['submitted']} requests")

# ---- leg 2: elastic fleet, same trace, same faults -----------------
os.environ["SLATE_TPU_SCALE"] = POLICY
svc = build()
assert svc._scaler is not None, "SLATE_TPU_SCALE failed to arm"
metrics.reset()  # evidence window: the measured replay only

peak = {"n": 1}
watch_stop = threading.Event()
def _watch():
    while not watch_stop.is_set():
        with svc._cond:
            n = len(svc._replicas)
        peak["n"] = max(peak["n"], n)
        time.sleep(0.02)
watcher = threading.Thread(target=_watch, daemon=True)
watcher.start()

faults.on()
res_elastic = replay.replay(svc, rows, speed=1.0, seed=0)
faults.reset()  # teardown proper: the tail drain runs untaxed
# quiet tail: the scaler must give the burst capacity back on its own
deadline = time.monotonic() + 30
while time.monotonic() < deadline:
    with svc._cond:
        n_end = len(svc._replicas)
    if n_end == 1:
        break
    time.sleep(0.05)
watch_stop.set()
watcher.join(2)
compiles = int(metrics.counters().get("jit.compilations", 0))
# zero-steady-state-compiles accounting: a scale-up lane's device
# prime inside add_replica IS a counted backend compile
# (serve.device_primes — cold-start budget, pre-traffic).  The gate
# claim is about the DISPATCH path: every compile in the window must
# be such a prime, so steady-state compiles = total - primes.
primes = int(metrics.counters().get("serve.device_primes", 0))

gate.publish({
    "static_p99_s": res_static["p99_s"] or 0.0,
    "elastic_p99_s": res_elastic["p99_s"] or 0.0,
    "budget_s": BUDGET_S,
    "replica_peak": peak["n"],
    "replicas_end": n_end,
    "min_replicas": 1,
    "max_replicas": 3,
    "up_threshold": 1.0,
    "new_lane_compiles": compiles - primes,
    "device_primes": primes,
})
svc.stop(drain=True, drain_timeout=120)
metrics.dump()
print(f"elastic leg: p99={(res_elastic['p99_s'] or 0) * 1e3:.1f}ms, "
      f"peak={peak['n']} lanes, end={n_end}, "
      f"steady-state compiles={compiles - primes} "
      f"({primes} pre-traffic lane primes)")
"""


def scale_gate() -> int:
    """Elastic-capacity gate, two legs: (1) the scale suite (pure
    controller/aggregator/warmup-plan units plus the live add/remove
    lifecycle tests); (2) the burst drill — one recorded bursty trace
    replayed against a static fleet (must MISS its p99 budget) and an
    elastic fleet (must HOLD it inside max_replicas, warm every new
    lane from artifacts with zero compiles, and return to
    min_replicas) — judged by tools/capacity_report.py."""
    import tempfile

    here = os.path.dirname(os.path.abspath(__file__)) or "."
    rc = subprocess.call(
        [sys.executable, "-m", "pytest", "tests/test_scale.py", "-q",
         "-p", "no:cacheprovider", "-p", "no:xdist", "-p", "no:randomly"],
        env=dict(os.environ, JAX_PLATFORMS="cpu"), cwd=here,
    )
    if rc != 0:
        return rc
    with tempfile.TemporaryDirectory(prefix="slate_scale_") as td:
        env = dict(
            os.environ, JAX_PLATFORMS="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count=8",
        )
        for var in ("SLATE_TPU_FAULTS", "SLATE_TPU_FACTOR_CACHE",
                    "SLATE_TPU_TENANTS", "SLATE_TPU_ADAPTIVE",
                    "SLATE_TPU_INTEGRITY", "SLATE_TPU_WARMUP",
                    "SLATE_TPU_ARTIFACTS", "SLATE_TPU_SCALE"):
            env.pop(var, None)
        jsonl = os.path.join(td, "scale.jsonl")
        art = os.path.join(td, "artifacts")
        trace = os.path.join(td, "burst.jsonl")
        # the burst drill runs under the instrumented sync runtime too:
        # the add/remove replica lifecycle is the lock-heaviest path in
        # the tree (same arming as the soak drill)
        rc = subprocess.call(
            [sys.executable, "-c", _SCALE_DRIVER, art, trace],
            env=dict(env, SLATE_TPU_METRICS=jsonl,
                     SLATE_TPU_SYNC_CHECK="1"),
            cwd=here,
        )
        if rc != 0:
            return rc
        rc = subprocess.call(
            [sys.executable, os.path.join("tools", "capacity_report.py"),
             jsonl],
            cwd=here,
        )
    return rc


# Fleet drill: one router (this process) + two REAL spawned worker
# processes on CPU.  host0 carries an SDC stream (sdc_solve:every=2),
# host1 a latency tax — the router's full certification, quarantine,
# re-dispatch and lifecycle planes must contain both.  Phases: SDC
# quarantine + probe recovery, fleet-wide quota abuse, a real SIGKILL
# host death (chaos site host_death) with respawn -> rejoin -> forced
# probe, injected rpc timeouts + a partition, then the observability
# fan-in (per-host dumps, stitched trace, orphan gauge).  Every
# delivery is reference-checked client-side (note_bad_result) — the
# drill only publishes evidence; tools/fleet_report.py is the judge.
_FLEET_DRIVER = """
import os
import subprocess
import sys
import time
import numpy as np
from slate_tpu.aux import faults, metrics, spans
from slate_tpu.exceptions import SlateError
from slate_tpu.fleet.router import (
    FleetRouter, note_bad_result, note_trace_orphans,
)
from slate_tpu.serve.service import Rejected

outdir, repo = sys.argv[1], sys.argv[2]

metrics.on()
metrics.reset()
spans.on(ring=65536)

N = 12
rng = np.random.default_rng(3)
A = (rng.standard_normal((N, N)) + N * np.eye(N)).astype(np.float32)

def prob(seed):
    return np.random.default_rng(seed).standard_normal(
        (N, 2)).astype(np.float32)

base = {
    "JAX_PLATFORMS": "cpu",
    "SLATE_TPU_METRICS": "1",
    "SLATE_TPU_TRACE_RING": "65536",
    "SLATE_TPU_SYNC_CHECK": "1",
    "SLATE_TPU_FAULTS": None,
}
host0 = dict(base, SLATE_TPU_FAULTS="sdc_solve:every=2")
host1 = dict(base, SLATE_TPU_FAULTS="latency:every=3,ms=40")

r = FleetRouter(
    spawn=2, cert="full",
    tenants="abuser:rate=4,burst=4;victim:rate=500,burst=100",
    heartbeat_s=0.2, rpc_timeout_s=30.0, dead_after=2,
    redispatch_max=2, hedge_s=1.0, respawn=True,
    quarantine_cooldown_s=0.4, spawn_env=[host0, host1], seed=7,
)
r.start()

checked = [0]

def solve(tenant="victim", seed=0):
    B = prob(seed)
    try:
        X = r.submit("gesv", A, B, deadline=60.0,
                     tenant=tenant).result(timeout=120)
    except Exception as e:
        return e
    # NaN-safe reference check: any non-finite or off-fence entry is a
    # silent wrong answer the defenses let through
    if not np.all(np.abs(A @ X - B) <= 1e-2):
        note_bad_result()
    checked[0] += 1
    return None

# ---- phase 1: SDC containment, quarantine + probe recovery ---------
for i in range(120):
    e = solve(seed=100 + i)
    assert e is None, f"victim solve failed under SDC: {e!r}"
    c = metrics.counters()
    if (c.get("fleet.quarantined", 0) >= 1
            and c.get("fleet.unquarantined", 0) >= 1):
        break
    time.sleep(0.01)
c = metrics.counters()
assert c.get("fleet.quarantined", 0) >= 1, "sdc host never quarantined"
assert c.get("fleet.unquarantined", 0) >= 1, "quarantine never probed back"
print(f"phase 1: quarantine engaged+recovered after {i + 1} solves")

# ---- phase 2: fleet-wide quota (abuser refused, victim whole) ------
rejected = 0
for i in range(14):
    e = solve(tenant="abuser", seed=200 + i)
    if e is not None:
        assert isinstance(e, Rejected), f"abuser got {e!r}, not Rejected"
        rejected += 1
assert rejected > 0, "abuser burst never hit the fleet-wide quota"
for i in range(6):
    e = solve(seed=300 + i)
    assert e is None, f"victim starved during abuse: {e!r}"
print(f"phase 2: abuser rejected {rejected}/14, victim served")

# ---- phase 3: real host death (SIGKILL) + fail-fast re-dispatch ----
# contract: every future RESOLVES — a correct re-dispatched answer or
# a TYPED error (the sole survivor may be the SDC lane, whose cert
# failures have no re-dispatch target until the respawn) — none hang,
# none deliver garbage (solve() reference-checks every delivery)
faults.configure("host_death:once")
faults.on()
delivered3 = 0
for i in range(10):
    e = solve(seed=400 + i)
    if e is None:
        delivered3 += 1
    else:
        assert isinstance(e, SlateError), f"untyped failure: {e!r}"
# death is DECLARED by the liveness plane (heartbeat misses reaching
# dead_after), not by the request path — the 10 solves above can
# finish inside a single beat, so give the monitor a few beats
deadline = time.monotonic() + 10
while time.monotonic() < deadline:
    if metrics.counters().get("fleet.host_dead", 0) >= 1:
        break
    time.sleep(0.05)
c = metrics.counters()
assert c.get("fleet.host_dead", 0) >= 1, "death was never declared"
assert c.get("fleet.redispatched", 0) >= 1, "no re-dispatch recovered it"
assert delivered3 >= 1, "no request survived the host death"
print(f"phase 3: host died, 10/10 futures resolved "
      f"({delivered3} delivered)")

# ---- phase 4: respawn -> rejoin -> forced certification probe ------
# a rejoined host only turns live once one of its deliveries is
# force-certified, so traffic must keep flowing while we wait (the
# probe rides a routed solve — either picked directly or via the
# re-dispatch of a cert failure on the SDC lane)
deadline = time.monotonic() + 60
states = {}
while time.monotonic() < deadline:
    states = {k: v["state"] for k, v in r.health()["hosts"].items()}
    if all(s == "live" for s in states.values()):
        break
    e = solve(seed=510)
    if e is not None:
        assert isinstance(e, SlateError), f"untyped failure: {e!r}"
    time.sleep(0.05)
assert all(s == "live" for s in states.values()), (
    f"dead host never rejoined live (states={states})")
assert metrics.counters().get("fleet.host_respawned", 0) >= 1, (
    "death was absorbed without a respawn")
for i in range(12):
    e = solve(seed=500 + i)
    assert e is None, f"victim solve failed after rejoin: {e!r}"
print("phase 4: host respawned, probe-certified, serving again")

# ---- phase 5: rpc timeouts + a partition, absorbed by retry --------
faults.configure("rpc_timeout:every=4;host_partition:once")
delivered5 = 0
for i in range(12):
    e = solve(seed=600 + i)
    if e is None:
        delivered5 += 1
    else:
        assert isinstance(e, SlateError), f"untyped failure: {e!r}"
faults.reset()
assert delivered5 >= 9, (
    f"timeouts/partition overwhelmed the fleet: {delivered5}/12")
print(f"phase 5: timeouts/partition absorbed ({delivered5}/12 delivered)")

# ---- fan-in: per-host dumps, stitched trace, orphan gauge ----------
replies = r.dump_hosts(outdir)
assert len(replies) == 2, f"expected both hosts to dump, got {replies}"
router_trace = os.path.join(outdir, "router.trace.json")
spans.export_chrome(router_trace, process_name="router")
traces = [router_trace] + sorted(
    os.path.join(outdir, f) for f in os.listdir(outdir)
    if f.endswith(".trace.json") and not f.startswith("router")
)
out = subprocess.run(
    [sys.executable, os.path.join(repo, "tools", "trace_stitch.py"),
     "--allow-orphans",
     "-o", os.path.join(outdir, "stitched.trace.json"), *traces],
    capture_output=True, text=True,
)
assert out.returncode == 0, out.stdout + out.stderr
line = out.stdout.strip().splitlines()[-1]
note_trace_orphans(int(line.rpartition("orphans=")[2]))
print(line)
r.stop(drain=True)
metrics.dump()
print(f"fleet drill: {checked[0]} reference-checked deliveries")
"""


# Escape leg: the SAME SDC stream with certification off — corrupted
# deliveries now reach the client, the reference check counts them
# (fleet.bad_results), and tools/fleet_report.py MUST exit nonzero.
_FLEET_ESCAPE_DRIVER = """
import sys
import numpy as np
from slate_tpu.aux import metrics
from slate_tpu.fleet.router import FleetRouter, note_bad_result

metrics.on()
metrics.reset()

N = 12
rng = np.random.default_rng(3)
A = (rng.standard_normal((N, N)) + N * np.eye(N)).astype(np.float32)

host0 = {
    "JAX_PLATFORMS": "cpu",
    "SLATE_TPU_FAULTS": "sdc_solve:every=2",
    "SLATE_TPU_METRICS": None,
    "SLATE_TPU_TRACE_RING": None,
}
r = FleetRouter(spawn=1, cert="off", heartbeat_s=0.25,
                rpc_timeout_s=30.0, spawn_env=[host0], seed=7)
r.start()
bad = 0
for i in range(8):
    B = np.random.default_rng(700 + i).standard_normal(
        (N, 2)).astype(np.float32)
    X = r.submit("gesv", A, B, deadline=60.0).result(timeout=120)
    if not np.all(np.abs(A @ X - B) <= 1e-2):
        note_bad_result()
        bad += 1
r.stop(drain=True)
metrics.dump()
print(f"escape leg: {bad} silent wrong answers delivered (cert off)")
assert bad > 0, "sdc stream produced no corrupt delivery to flag"
"""


def fleet_gate() -> int:
    """Cross-process defense gate, three legs: (1) the fleet suite
    (wire framing, router edge cases — exactly-once under host death
    with a hedge twin inflight, drain racing re-dispatch, stats-only
    reports after death, forced rejoin probes — the worker front-end,
    and the stitch/merge/report tools); (2) the 3-process CPU drill —
    router + 2 spawned workers, host0 carrying an SDC stream and host1
    a latency tax, driven through quota abuse, a real SIGKILL host
    death with respawn/rejoin/probe, and injected rpc timeouts +
    partition, its per-host dumps merged (``metrics_merge --tag``) and
    traces stitched (``trace_stitch``), judged by
    tools/fleet_report.py; (3) the escape proof: certification off,
    the same SDC — the report MUST exit nonzero."""
    import tempfile

    here = os.path.dirname(os.path.abspath(__file__)) or "."
    rc = subprocess.call(
        [sys.executable, "-m", "pytest", "tests/test_fleet.py", "-q",
         "-p", "no:cacheprovider", "-p", "no:xdist", "-p", "no:randomly"],
        env=dict(os.environ, JAX_PLATFORMS="cpu"), cwd=here,
    )
    if rc != 0:
        return rc
    with tempfile.TemporaryDirectory(prefix="slate_fleet_") as td:
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        for var in ("SLATE_TPU_FAULTS", "SLATE_TPU_FACTOR_CACHE",
                    "SLATE_TPU_TENANTS", "SLATE_TPU_ADAPTIVE",
                    "SLATE_TPU_INTEGRITY", "SLATE_TPU_WARMUP",
                    "SLATE_TPU_ARTIFACTS", "SLATE_TPU_SCALE",
                    "SLATE_TPU_FLEET", "SLATE_TPU_FLEET_TENANTS"):
            env.pop(var, None)
        outdir = os.path.join(td, "dumps")
        os.makedirs(outdir)
        jsonl = os.path.join(td, "router.jsonl")
        # the drill's router AND both workers run the instrumented
        # sync runtime: every router<->host lock edge in the drill is
        # order-checked against LOCK_ORDER.json
        rc = subprocess.call(
            [sys.executable, "-c", _FLEET_DRIVER, outdir, here],
            env=dict(env, SLATE_TPU_METRICS=jsonl,
                     SLATE_TPU_SYNC_CHECK="1"),
            cwd=here,
        )
        if rc != 0:
            return rc
        host_dumps = sorted(
            os.path.join(outdir, f) for f in os.listdir(outdir)
            if f.endswith(".metrics.jsonl")
        )
        merged = os.path.join(td, "merged.jsonl")
        cmd = [sys.executable, os.path.join("tools", "metrics_merge.py"),
               "-o", merged]
        for tag in ["router"] + [
            os.path.basename(p).split(".")[0] for p in host_dumps
        ]:
            cmd += ["--tag", tag]
        cmd += [jsonl] + host_dumps
        rc = subprocess.call(cmd, cwd=here)
        if rc != 0:
            return rc
        rc = subprocess.call(
            [sys.executable, os.path.join("tools", "fleet_report.py"),
             merged, "--victim", "victim", "--p99-budget", "15",
             "--require-stitch"],
            cwd=here,
        )
        if rc != 0:
            return rc
        esc = os.path.join(td, "escape.jsonl")
        rc = subprocess.call(
            [sys.executable, "-c", _FLEET_ESCAPE_DRIVER],
            env=dict(env, SLATE_TPU_METRICS=esc,
                     SLATE_TPU_SYNC_CHECK="1"),
            cwd=here,
        )
        if rc != 0:
            return rc
        rc = subprocess.call(
            [sys.executable, os.path.join("tools", "fleet_report.py"),
             esc],
            cwd=here,
        )
        if rc == 0:
            print("fleet gate: report failed to flag an undefended "
                  "SDC escape across the fleet")
            return 1
    return 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--tier1", action="store_true",
                    help="run the exact ROADMAP tier-1 gate (870 s timeout, "
                         "DOTS_PASSED accounting) and exit")
    ap.add_argument("--schedules", action="store_true",
                    help="run the factorization-schedule parity smoke "
                         "(recursive vs flat vs scipy) and exit")
    ap.add_argument("--chaos", action="store_true",
                    help="run the fault-injection suite (slow matrix "
                         "included) + the chaos_report recovery gate")
    ap.add_argument("--refine", action="store_true",
                    help="run the mixed-precision refinement suite + the "
                         "refine_report fallback-rate gate")
    ap.add_argument("--coldstart", action="store_true",
                    help="run the artifact suite + the restart drill "
                         "(fresh-process restore with 0 compiles, "
                         "byte-flip recovery) + the artifact_report "
                         "chaos gate")
    ap.add_argument("--sharded", action="store_true",
                    help="run the placement suite (replica scale-out + "
                         "spmd routing on a forced 8-device CPU mesh) + "
                         "the placement_report starvation gate")
    ap.add_argument("--latency", action="store_true",
                    help="run the span/histogram suites + a traced "
                         "faulty serve stream (Chrome-export chain "
                         "check) + the latency_report p99 gate")
    ap.add_argument("--factor", action="store_true",
                    help="run the factor-cache suite + an "
                         "env-activated repeated-A stream gated by "
                         "tools/factor_report.py (zero hits on a "
                         "repeated-A stream fails)")
    ap.add_argument("--fabric", action="store_true",
                    help="run the factor-fabric gate: the fabric suite "
                         "(arena + streaming sessions) + an "
                         "env-activated repeated-A gels session stream "
                         "(1 factor, >= 20 warmed solves, 0 compiles, "
                         "upload_avoided_bytes > 0; factor_report "
                         "verdict) + an arena-off leg proving "
                         "byte-identical legacy serving")
    ap.add_argument("--adaptive", action="store_true",
                    help="run the admission suite + the bursty "
                         "two-tenant stream (static config misses the "
                         "victim's p99 budget, adaptive holds it and "
                         "sheds the abuser; tenant_report verdict) + "
                         "the tenant_flood chaos join")
    ap.add_argument("--perf", action="store_true",
                    help="run the devmon suite + the bench_diff "
                         "regression sentinel (true pair passes, "
                         "synthetic regression fails) + a devmon "
                         "serve stream classified by roofline_report "
                         "+ a quick bench floored against "
                         "BENCH_FLOOR_CPU.json")
    ap.add_argument("--integrity", action="store_true",
                    help="run the integrity suite + the four-phase SDC "
                         "drill (sdc_factor/sdc_solve over a warmed "
                         "mixed stream: zero silent wrong answers, "
                         "quarantine engage/recover, hedges win) "
                         "judged by tools/integrity_report.py, + the "
                         "escape proof (plane off -> report nonzero)")
    ap.add_argument("--lint", action="store_true",
                    help="run the slate-lint suite + a budgeted "
                         "full-tree static-analysis pass including the "
                         "whole-program race rules (nonzero on any new "
                         "finding; see README 'Static analysis')")
    ap.add_argument("--race", action="store_true",
                    help="run the race/deadlock gate: the race suite, "
                         "the whole-program static rules + lock-order "
                         "graph artifact check, an instrumented "
                         "chaos/hedge/drain stress leg under "
                         "SLATE_TPU_SYNC_CHECK judged by "
                         "tools/race_report.py, and two planted "
                         "fixtures the report MUST flag")
    ap.add_argument("--soak", action="store_true",
                    help="run the trace-driven soak gate: the soak "
                         "suite + ~10^4 replayed requests against a "
                         "fully-armed service under faults with the "
                         "record->replay round trip and determinism "
                         "checks, judged by tools/soak_report.py, + "
                         "the escape proof (defenses off -> report "
                         "nonzero)")
    ap.add_argument("--full", action="store_true",
                    help="with --soak: scale the drill to ~10^6 "
                         "requests (tens of minutes)")
    ap.add_argument("--scale", action="store_true",
                    help="run the elastic-capacity gate: the scale "
                         "suite + one recorded bursty trace replayed "
                         "static (misses p99) then elastic (holds it, "
                         "artifact-warmed lanes, fleet returns to "
                         "min), judged by tools/capacity_report.py")
    ap.add_argument("--fleet", action="store_true",
                    help="run the cross-process defense gate: the "
                         "fleet suite + the 3-process drill (router + "
                         "2 spawned workers under SDC/latency/host "
                         "death/timeouts, per-host dumps merged and "
                         "traces stitched) judged by "
                         "tools/fleet_report.py, + the escape proof "
                         "(certification off -> report nonzero)")
    ap.add_argument("routines", nargs="*", default=[])
    ap.add_argument("--size", default="quick", choices=sorted(PRESETS))
    ap.add_argument("--grid", default="1x1")
    ap.add_argument("--xml", default=None)
    ap.add_argument("--target", default="d")
    ap.add_argument("--type", default=None)
    args = ap.parse_args()

    if args.tier1:
        return tier1()
    if args.schedules:
        return schedules_smoke()
    if args.chaos:
        return chaos()
    if args.refine:
        return refine_gate()
    if args.coldstart:
        return coldstart()
    if args.sharded:
        return sharded()
    if args.latency:
        return latency_gate()
    if args.factor:
        return factor_gate()
    if args.fabric:
        return fabric_gate()
    if args.adaptive:
        return adaptive_gate()
    if args.perf:
        return perf_gate()
    if args.integrity:
        return integrity_gate()
    if args.lint:
        return lint_gate()
    if args.race:
        return race_gate()
    if args.soak:
        return soak_gate(full=args.full)
    if args.scale:
        return scale_gate()
    if args.fleet:
        return fleet_gate()

    # virtual devices for multi-process grids (tests force the cpu
    # platform; the TPU plugin ignores JAX_PLATFORMS so set via config)
    p, q = (int(x) for x in args.grid.split("x"))
    if p * q > 1:
        os.environ.setdefault(
            "XLA_FLAGS",
            f"--xla_force_host_platform_device_count={max(8, p * q)}",
        )
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax

    jax.config.update("jax_enable_x64", True)

    from slate_tpu.testing.tester import run

    preset = PRESETS[args.size]
    argv = list(args.routines) if args.routines else ["all"]
    argv += ["--dim", preset["dim"], "--nb", preset["nb"]]
    argv += ["--type", args.type or preset["type"]]
    argv += ["--grid", args.grid, "--target", args.target]
    if args.xml:
        argv += ["--xml", args.xml]
    return run(argv)


if __name__ == "__main__":
    sys.exit(main())
