#!/usr/bin/env python
"""Headline benchmark sweep over the driver stack on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "extra"}.

Headline metric: sgemm GFLOP/s per chip in the single-pass MXU mode
(SLATE_TPU_FAST_F32, the mode BENCH_r01 measured).  Baseline: the
reference's only published figure, dgemm 0.70 TFLOP/s per GPU (reference
docs/usage.md:40-42; see BASELINE.md).  vs_baseline = GFLOP/s / 700.

"extra" carries the north-star routine entries (BASELINE.json asks for
gemm/potrf/getrf/geqrf/heev): dgemm + f64 factorizations + the two-stage
heev values path, each with GFLOP/s and seconds.  f32 accurate-mode gemm
(the product default after the precision policy) is reported alongside
the fast mode.  See BENCH_NOTES.md for methodology and regression notes.

Time budget (BENCH_r05 died at rc=124 mid-sweep with NO output): every
entry runs under a deadline (--budget seconds, default 780 — inside the
driver's typical 900 s timeout).  When the remaining budget dips below
the reserve, the remaining entries are recorded as {"skipped": "time
budget"} and the final JSON line still prints, so a partial sweep is a
diagnosable artifact instead of a dead log.  --quick shrinks sizes and
trial counts for smoke runs.

Per-entry observability: metrics (slate_tpu.aux.metrics) are ON for the
whole sweep; each entry runs inside metrics.context(label) and reports
its jit compilation delta + wall seconds in extra[label]["metrics"].
Set SLATE_TPU_METRICS=/path/out.jsonl to keep the full event stream.
"""

import argparse
import json
import os
import time

import numpy as np

# Persistent XLA compilation cache: the native blocked factorization
# kernels compile in minutes over this toolchain the first time; cached
# executables load in seconds on every later run.
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.expanduser("~"), ".cache", "jax_comp"),
)
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1.0")


def _gflops(name, hand_flops, best_s):
    """GFLOP/s with the numerator from the build-time registry record
    when one exists (metrics.costs(), populated by _bench's devmon
    capture; the BENCH_NOTES demand — measured program, not a derived
    formula), keeping the hand formula as a cross-check.  XLA reports
    -1 for unknowable costs (e.g. CPU while loops): that is "no data",
    never zero, so the model numerator is used and the source is
    labeled.  The registry's memory_analysis fields ride along so the
    trajectory is bench_diff-able on peak memory, not just rates."""
    from slate_tpu.aux import metrics

    out = {"gflops_model": round(hand_flops / best_s / 1e9, 1)}
    rec = metrics.costs().get(name, {})
    xla = rec.get("flops", -1.0)
    if xla is not None and xla > 0:
        out["gflops"] = round(xla / best_s / 1e9, 1)
        out["flops_source"] = "xla_cost_analysis"
    else:
        out["gflops"] = out["gflops_model"]
        out["flops_source"] = "model"
    if rec.get("bytes_accessed"):
        out["bytes_accessed"] = int(rec["bytes_accessed"])
    if rec.get("peak_bytes"):
        out["peak_bytes"] = int(rec["peak_bytes"])
    return out


def _bench(step_fn, warm_args, trials, name=None):
    """Best-of wall time with host readback as the barrier.  With a
    name, the step is AOT-compiled ONCE via the devmon capture path
    (lower -> compile -> cost_analysis + memory_analysis), so the one
    compile every entry pays anyway is also the flops/bytes/peak-
    memory evidence — on every backend, with no AOT second compile
    (the per-call capture this replaces defaulted OFF on accelerators
    and left flops_source "no data" there); the compiled executable is
    then metrics-instrumented for the compile/run timer split.
    Deliberately NOT metrics.measure_best: the steps here carry the
    trial perturbation IN the jitted signature (t) and chain K
    dependent ops — re-wrapping them in measure_best's scalarizer
    would change the measured program."""
    if name is not None:
        from slate_tpu.aux import devmon, metrics

        t0 = time.perf_counter()
        compiled, _cost = devmon.capture_jitted(
            step_fn, (*warm_args, 0.0), name=name
        )
        if compiled is not None:
            # the AOT capture WAS the entry's backend compile: record
            # it under the compile timer/counters ourselves; the
            # wrapper below is told the executable is precompiled so
            # every dispatch (first warm call included) logs as a run
            metrics.observe(f"{name}.compile", time.perf_counter() - t0)
            metrics.inc("jit.compilations")
            metrics.inc(f"{name}.compilations")
            step_fn = compiled  # reuse the capture compile as the build
        step_fn = metrics.instrument_jit(
            step_fn, name, precompiled=compiled is not None
        )
    float(step_fn(*warm_args, 0.0))  # compile + warmup
    best = float("inf")
    for trial in range(trials):
        t0 = time.perf_counter()
        s = float(step_fn(*warm_args, 1.0 + trial))
        best = min(best, time.perf_counter() - t0)
        assert np.isfinite(s)
    return best


def bench_gemm(jax, jnp, n, nb, dtype, K, trials):
    from slate_tpu.drivers import blas3
    from slate_tpu.matrix.matrix import Matrix

    key = jax.random.PRNGKey(0)
    ka, kb = jax.random.split(key)
    A = Matrix.from_global(jax.random.normal(ka, (n, n), dtype), nb)
    B = Matrix.from_global(jax.random.normal(kb, (n, n), dtype) * (1.0 / n), nb)

    @jax.jit
    def step(A, B, t):
        # t varies per trial so no layer can serve a cached result; the
        # K-chain amortizes per-dispatch tunnel latency (~100ms)
        C = A._with(data=A.data + t)
        for _ in range(K):
            C = blas3.gemm(1.0, C, B, 0.0, C)
        return C.data.sum()

    # the name carries mode + K: fast-f32 and accurate-f32 run different
    # programs of different chain lengths and must not share timers/costs
    mode = "fast" if os.environ.get("SLATE_TPU_FAST_F32") == "1" else "hi"
    name = f"bench.gemm_{jnp.dtype(dtype).name}_{mode}_n{n}_K{K}"
    best = _bench(step, (A, B), trials, name=name)
    # hand model 2n^3 per gemm x K chained; the xla numerator covers the
    # same whole step (K gemms + the reduction), so both rate the step
    return _gflops(name, 2.0 * n**3 * K, best), best / K


def bench_potrf(jax, jnp, n, nb, trials, schedule="auto"):
    import slate_tpu as st
    from slate_tpu.enums import Option

    key = jax.random.PRNGKey(1)
    G = jax.random.normal(key, (n, n), jnp.float64) / np.sqrt(n)
    S = G @ G.T + 2.0 * jnp.eye(n, dtype=jnp.float64)
    A = st.HermitianMatrix.from_global(S, nb, uplo=st.Uplo.Lower)
    opts = {Option.Schedule: schedule}

    @jax.jit
    def step(A, t):
        L, info = st.potrf(A._with(data=A.data + t * 1e-14), opts)
        return L.data.sum() + info

    name = f"bench.potrf_n{n}_{schedule}"
    best = _bench(step, (A,), trials, name=name)
    return _gflops(name, n**3 / 3.0, best), best


def bench_getrf(jax, jnp, n, nb, trials, schedule="auto"):
    import slate_tpu as st
    from slate_tpu.enums import Option

    key = jax.random.PRNGKey(2)
    G = jax.random.normal(key, (n, n), jnp.float64)
    A = st.Matrix.from_global(G + n * jnp.eye(n, dtype=jnp.float64), nb)
    opts = {Option.Schedule: schedule}

    @jax.jit
    def step(A, t):
        LU, piv, info = st.getrf(A._with(data=A.data + t * 1e-14), opts)
        return LU.data.sum() + info

    name = f"bench.getrf_n{n}_{schedule}"
    best = _bench(step, (A,), trials, name=name)
    return _gflops(name, 2.0 * n**3 / 3.0, best), best


def bench_geqrf(jax, jnp, n, nb, trials, schedule="auto"):
    import slate_tpu as st
    from slate_tpu.enums import Option

    key = jax.random.PRNGKey(3)
    A = st.Matrix.from_global(jax.random.normal(key, (n, n), jnp.float64), nb)
    opts = {Option.Schedule: schedule}

    @jax.jit
    def step(A, t):
        fac, T = st.geqrf(A._with(data=A.data + t * 1e-14), opts)
        return fac.data.sum()

    name = f"bench.geqrf_n{n}_{schedule}"
    best = _bench(step, (A,), trials, name=name)
    return _gflops(name, 4.0 * n**3 / 3.0, best), best


def bench_trsm(jax, jnp, routine, n, nrhs, trials, schedule="auto"):
    """The solve-phase trsm pair behind the serve ``phase="solve"``
    buckets — the factor cache's top-traffic hit path.  ``posv`` times
    potrs_from_global (lower + transposed-lower sweep against a clean
    Cholesky factor), ``gesv`` times getrs_from_global (unit-lower +
    upper sweep against a packed LU) — both triangles covered between
    the two.  ``schedule="pallas"`` routes both sweeps through the
    fused Pallas trsm kernels (interpret mode off-TPU)."""
    from jax import lax

    from slate_tpu.drivers.chol import potrs_from_global
    from slate_tpu.drivers.lu import getrs_from_global

    key = jax.random.PRNGKey(5)
    kf, kr = jax.random.split(key)
    G = jax.random.normal(kf, (n, n), jnp.float64) / np.sqrt(n)
    B = jax.random.normal(kr, (n, nrhs), jnp.float64)
    if routine == "posv":
        S = G @ G.T + 2.0 * jnp.eye(n, dtype=jnp.float64)
        F = jnp.linalg.cholesky(S)
        solve = potrs_from_global
    else:
        F, _piv, _perm = lax.linalg.lu(G + jnp.eye(n, dtype=jnp.float64))
        solve = getrs_from_global

    @jax.jit
    def step(F, B, t):
        return solve(F, B + t * 1e-12, schedule).sum()

    name = f"bench.trsm_{routine}_n{n}_{schedule}"
    best = _bench(step, (F, B), trials, name=name)
    # two O(n^2 nrhs) triangular sweeps per solve
    return _gflops(name, 2.0 * n * n * nrhs, best), best


def bench_solve_mixed(jax, jnp, routine, n, nb, trials):
    """Mixed-precision solve vs the plain f64 direct driver: wall
    seconds for both (eager best-of — the mixed drivers run the host
    fallback branch, so they are timed as the user calls them),
    refinement iteration count, and the speedup ratio.  Well-
    conditioned operands so the refine path never falls back (a
    fallback would time factor+direct and report speedup < 1 — which
    is exactly what the ratio is for)."""
    import slate_tpu as st

    key = jax.random.PRNGKey(6)
    G = jax.random.normal(key, (n, n), jnp.float64)
    B = jax.random.normal(jax.random.PRNGKey(7), (n, 8), jnp.float64)
    Bm = st.Matrix.from_global(B, nb)

    if routine == "posv":
        S = G @ G.T / n + 2.0 * jnp.eye(n, dtype=jnp.float64)

        def make_A(t):
            return st.HermitianMatrix.from_global(
                S + t * 1e-12 * jnp.eye(n, dtype=jnp.float64), nb,
                uplo=st.Uplo.Lower,
            )

        def plain(A):
            X, _L, info = st.posv(A, Bm)
            return X, int(info)

        def mixed(A):
            X, info, iters = st.posv_mixed(A, Bm)
            return X, iters
    else:
        Ad = G + n * jnp.eye(n, dtype=jnp.float64)

        def make_A(t):
            return st.Matrix.from_global(
                Ad + t * 1e-12 * jnp.eye(n, dtype=jnp.float64), nb
            )

        def plain(A):
            X, _LU, _piv, info = st.gesv(A, Bm)
            return X, int(info)

        def mixed(A):
            X, info, iters = st.gesv_mixed(A, Bm)
            return X, iters

    def best_of(fn):
        fn(make_A(0.0))  # compile + warm
        best, aux = float("inf"), None
        for t in range(trials):
            A = make_A(1.0 + t)
            jax.block_until_ready(A.data)
            t0 = time.perf_counter()
            X, a = fn(A)
            float(np.asarray(X.data.ravel()[-1]))  # host readback barrier
            best = min(best, time.perf_counter() - t0)
            aux = a
        return best, aux

    sec_plain, _ = best_of(plain)
    sec_mixed, iters = best_of(mixed)
    return {
        "n": n,
        "seconds": round(sec_mixed, 3),
        "seconds_plain": round(sec_plain, 3),
        "speedup_vs_plain": round(sec_plain / sec_mixed, 3),
        "iterations": int(iters),
    }


def bench_heev_vectors(jax, jnp, n, nb, trials):
    """Two-stage heev WITH eigenvectors: he2hb + hb2st wavefront +
    native stedc divide & conquer + both back-transforms — no vendor
    eigensolver anywhere on the path (the vendor f64 eigh is a compile
    bomb past n~512 on this toolchain)."""
    import slate_tpu as st

    key = jax.random.PRNGKey(4)
    G = jax.random.normal(key, (n, n), jnp.float64)
    S = (G + G.T) / 2
    A = st.HermitianMatrix.from_global(S, nb, uplo=st.Uplo.Lower)

    @jax.jit
    def step(A, t):
        w, Z = st.heev(A._with(data=A.data + t * 1e-14), vectors=True)
        return w.sum() + Z.data.ravel()[-1]

    name = f"bench.heev_vectors_n{n}"
    best = _bench(step, (A,), trials, name=name)
    # flop model for the WITH-vectors path: 4n^3/3 reduction + ~4n^3/3
    # D&C vector assembly + 2n^3 hb2st back-transform + 2n^3 he2hb
    # back-transform ~= 20n^3/3 (LAPACK dsyevd-style accounting), so the
    # rate is comparable across entries (ADVICE r3)
    return _gflops(name, 20.0 * n**3 / 3.0, best), best


def bench_heev_values(jax, jnp, n, nb, trials):
    """Two-stage heev, eigenvalues only: he2hb + hb2st wavefront +
    Sturm bisection — no vendor eigensolver anywhere on this path."""
    import slate_tpu as st

    key = jax.random.PRNGKey(4)
    G = jax.random.normal(key, (n, n), jnp.float64)
    S = (G + G.T) / 2
    A = st.HermitianMatrix.from_global(S, nb, uplo=st.Uplo.Lower)

    @jax.jit
    def step(A, t):
        w, _ = st.heev(A._with(data=A.data + t * 1e-14), vectors=False)
        return w.sum()

    name = f"bench.heev_values_n{n}"
    best = _bench(step, (A,), trials, name=name)
    return _gflops(name, 4.0 * n**3 / 3.0, best), best


def _progress(msg):
    """Stage marker on stderr (the JSON contract owns stdout): makes a
    wedged remote compile attributable from the driver's log."""
    import sys

    print(f"[bench] {msg}", file=sys.stderr, flush=True)


def main(argv=None):
    ap = argparse.ArgumentParser(prog="bench")
    ap.add_argument("--budget", type=float, default=780.0,
                    help="sweep deadline in seconds (0 = unlimited); "
                         "entries past it are recorded as skipped")
    ap.add_argument("--reserve", type=float, default=45.0,
                    help="stop starting entries when less than this many "
                         "seconds of budget remain")
    ap.add_argument("--quick", action="store_true",
                    help="CPU-scale sizes + minimal trials (smoke run)")
    ap.add_argument("--full", action="store_true",
                    help="historical flagship sizes (n=8192 factorizations, "
                         "staged heev up to 8192) — needs a raised --budget; "
                         "the default list is sized to fit the default "
                         "budget and exit 0 (BENCH_r05 died at rc=124)")
    args = ap.parse_args(argv)

    import jax

    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp

    from slate_tpu.aux import metrics

    metrics.on()
    # flops/bytes/peak-memory come from _bench's build-time devmon
    # capture (the AOT compile IS the entry's one build — no second
    # compile, so the numerators exist on accelerators too, where the
    # old per-call capture defaulted OFF and reported "no data")
    on_tpu = any(d.platform != "cpu" for d in jax.devices()) and not args.quick
    trials = 5 if on_tpu else 2
    extra = {}
    start = time.monotonic()
    deadline = start + args.budget if args.budget > 0 else None

    def run_entry(label, fn):
        """Run one bench entry under the budget: skipped entries are
        recorded (a partial sweep stays diagnosable — BENCH_r05 rc=124),
        each entry carries its wall seconds + jit compilation delta."""
        if deadline is not None and time.monotonic() > deadline - args.reserve:
            _progress(f"{label}: SKIPPED (time budget)")
            extra[label] = {"skipped": "time budget"}
            return None
        _progress(label)
        c0 = metrics.counters().get("jit.compilations", 0)
        t0 = time.monotonic()
        with metrics.context(label):
            try:
                entry = fn()
            except Exception as e:  # noqa: BLE001 — the JSON line must print
                entry = {"error": str(e)[:120]}
        entry["metrics"] = {
            "wall_s": round(time.monotonic() - t0, 2),
            "compilations": metrics.counters().get("jit.compilations", 0) - c0,
        }
        extra[label] = entry
        return entry

    # -- headline: fast-f32 sgemm (BENCH_r01's mode) ----------------------
    n = 8192 if on_tpu else 512

    def entry_sgemm_fast():
        os.environ["SLATE_TPU_FAST_F32"] = "1"
        rep, sec = bench_gemm(jax, jnp, n, 1024 if on_tpu else 128,
                              jnp.float32, 8 if on_tpu else 2, trials)
        return {"n": n, **rep}

    e = run_entry("sgemm_fast_f32", entry_sgemm_fast)
    gf_fast = e.get("gflops", 0.0) if e else 0.0

    # -- accurate-mode f32 gemm (product default) -------------------------
    def entry_sgemm_accurate():
        os.environ["SLATE_TPU_FAST_F32"] = "0"
        rep, _ = bench_gemm(jax, jnp, n, 1024 if on_tpu else 128,
                            jnp.float32, 4 if on_tpu else 2, trials)
        return {"n": n, **rep}

    run_entry("sgemm_accurate", entry_sgemm_accurate)

    # -- dgemm (the north-star dtype).  n stays 4096: the n=8192 f64
    # chain compile wedges the tunnel's remote-compile service (>2 h,
    # host idle); the honest n=8192 denominator (1,927 GF/s) is
    # measured out-of-band by tools/profile_factor.py and recorded in
    # BENCH_NOTES.md's ceiling analysis
    def entry_dgemm():
        nd = 4096 if on_tpu else 256
        rep, _ = bench_gemm(jax, jnp, nd, 512 if on_tpu else 128,
                            jnp.float64, 4 if on_tpu else 2, trials)
        return {"n": nd, **rep}

    run_entry("dgemm", entry_dgemm)

    # -- f64 factorizations, schedule=flat|recursive variants --------------
    # default sizes fit the default --budget (the 8192 flagships pushed
    # BENCH_r05 past its driver timeout: rc=124, no JSON); --full
    # restores them.  The recursive variants measure the exact-shape
    # divide & conquer schedules; extra[label]["flops_waste_ratio"]
    # carries the per-entry exec/model ratio from the factor.* counters.
    nfac = (8192 if args.full else 4096) if on_tpu else 128

    def factor_entry(label, fn, nsize, nb, schedule):
        def run():
            from slate_tpu.aux import metrics as _m

            c0 = _m.counters()
            rep, sec = fn(nsize, nb, schedule)
            c1 = _m.counters()
            dm = c1.get("factor.flops_model", 0) - c0.get(
                "factor.flops_model", 0
            )
            dx = c1.get("factor.flops_exec", 0) - c0.get(
                "factor.flops_exec", 0
            )
            entry = {"n": nsize, "schedule": schedule, **rep,
                     "seconds": round(sec, 3)}
            if dm > 0:
                entry["flops_waste_ratio"] = round(dx / dm, 3)
            return entry

        return run_entry(label, run)

    nbfac = 512 if on_tpu else 32
    npo = nfac if on_tpu else 256
    nbpo = nbfac if on_tpu else 64

    def _potrf(nn, nb, s):
        return bench_potrf(jax, jnp, nn, nb, trials, s)

    def _getrf(nn, nb, s):
        return bench_getrf(jax, jnp, nn, nb, trials, s)

    def _geqrf(nn, nb, s):
        return bench_geqrf(jax, jnp, nn, nb, trials, s)

    factor_entry("dpotrf", _potrf, npo, nbpo, "flat")
    factor_entry("dpotrf_recursive", _potrf, npo, nbpo, "recursive")
    factor_entry("dgetrf", _getrf, nfac, nbfac, "flat")
    factor_entry("dgetrf_recursive", _getrf, nfac, nbfac, "recursive")
    factor_entry("dgeqrf", _geqrf, nfac, nbfac, "flat")
    factor_entry("dgeqrf_recursive", _geqrf, nfac, nbfac, "recursive")

    # -- solve-phase trsm pair (the serve factor cache's top-traffic
    # hit path — phase="solve" buckets).  Both triangles between the
    # two routines, vendor vs fused-Pallas schedule variants -----------
    ntr = (8192 if args.full else 4096) if on_tpu else 256
    nrhs_tr = 512 if on_tpu else 64

    def trsm_entry(label, routine, schedule):
        def run():
            rep, sec = bench_trsm(
                jax, jnp, routine, ntr, nrhs_tr, trials, schedule
            )
            return {"n": ntr, "nrhs": nrhs_tr, "schedule": schedule,
                    **rep, "seconds": round(sec, 4)}

        return run_entry(label, run)

    trsm_entry("dtrsm_posv", "posv", "auto")
    trsm_entry("dtrsm_posv_pallas", "posv", "pallas")
    trsm_entry("dtrsm_gesv", "gesv", "auto")
    trsm_entry("dtrsm_gesv_pallas", "gesv", "pallas")

    # -- mixed-precision solves (refine/): f32-factor IR vs plain f64.
    # speedup_vs_plain is the headline the subsystem exists for: on the
    # MXU the f32 factor runs several times faster than the emulated-
    # f64 one, and the O(n^2) refinement is noise at these sizes -------
    nmix = (4096 if args.full else 2048) if on_tpu else 256

    def entry_mixed(routine):
        def run():
            return bench_solve_mixed(
                jax, jnp, routine, nmix, 512 if on_tpu else 32, trials
            )

        return run

    run_entry("dgesv_mixed", entry_mixed("gesv"))
    run_entry("dposv_mixed", entry_mixed("posv"))

    # -- serving scale-out: the same warmed request stream at
    # replicas=1 vs replicas=N (fake CPU devices here, real chips when
    # available — on one physical CPU the replicas share cores, so the
    # honest headline is the dispatch spread + requests/s pair, not a
    # speedup claim; BENCH_r06 tracks the curve) ----------------------
    def entry_serve_scaling():
        from slate_tpu.aux import metrics as _m
        from slate_tpu.serve import buckets as _bk
        from slate_tpu.serve.cache import ExecutableCache
        from slate_tpu.serve.placement import PlacementPolicy
        from slate_tpu.serve.service import SolverService

        ndev = len(jax.devices())
        nrep = max(2, min(4, ndev))
        nserve = 512 if on_tpu else 64
        reqs = 48
        rng = np.random.default_rng(0)
        probs = [
            (rng.standard_normal((nserve, nserve)) + nserve * np.eye(nserve),
             rng.standard_normal((nserve, 4)))
            for _ in range(8)
        ]
        out = {"n": nserve, "requests": reqs, "devices": ndev}
        rates = {}
        for nrep_i in (1, nrep):
            # factor_cache=False: this entry measures dispatch spread,
            # and an env-armed cache would detour the repeated-A probs
            # onto unwarmed solve buckets (cold compiles mid-stream)
            svc = SolverService(
                cache=ExecutableCache(manifest_path=None), batch_max=8,
                batch_window_s=0.001,
                placement=PlacementPolicy(replicas=nrep_i),
                factor_cache=False,
            )
            key = _bk.bucket_for("gesv", nserve, nserve, 4, np.float64)
            svc.cache.ensure_manifest(key, (1, 8))
            svc.warmup()  # compile-free stream: rates measure dispatch
            c0 = _m.counters().get("serve.replicated_dispatch", 0)
            t0 = time.perf_counter()
            with _m.deltas() as d:
                futs = [
                    svc.submit("gesv", *probs[i % len(probs)])
                    for i in range(reqs)
                ]
                for f in futs:
                    assert np.all(np.isfinite(f.result(timeout=600)))
            dt = time.perf_counter() - t0
            svc.stop()
            rates[nrep_i] = reqs / dt
            rep = {
                "requests_per_s": round(reqs / dt, 1),
                "seconds": round(dt, 3),
                "replicated_dispatch": int(
                    _m.counters().get("serve.replicated_dispatch", 0) - c0
                ),
            }
            # tail latency alongside throughput (BENCH_r06+ tracks the
            # p99 curve, not just requests/s): the serve.latency
            # histograms windowed to this config's stream
            lat = d.hist(f"serve.latency.{key.label}.total")
            if lat:
                rep.update(
                    p50_ms=round(lat["p50"] * 1e3, 2),
                    p95_ms=round(lat["p95"] * 1e3, 2),
                    p99_ms=round(lat["p99"] * 1e3, 2),
                )
            out[f"replicas_{nrep_i}"] = rep
        out["scaling_x"] = round(rates[nrep] / max(rates[1], 1e-9), 2)
        return out

    run_entry("serve_scaling", entry_serve_scaling)

    # -- serving tail latency: one warmed replica, a mixed small/large
    # stream, and the queued/execute/total percentile split per bucket
    # (the SLO surface; tools/latency_report.py renders the same table
    # from a SLATE_TPU_METRICS JSONL) --------------------------------
    def entry_serve_latency():
        from slate_tpu.aux import metrics as _m
        from slate_tpu.serve import buckets as _bk
        from slate_tpu.serve.cache import ExecutableCache
        from slate_tpu.serve.service import SolverService

        nsm = 256 if on_tpu else 24
        nlg = 512 if on_tpu else 48
        reqs = 64

        def prob(n, seed):
            r = np.random.default_rng(seed)
            return (r.standard_normal((n, n)) + n * np.eye(n),
                    r.standard_normal((n, 4)))

        probs = [prob(nsm, i) for i in range(6)] + [
            prob(nlg, 100 + i) for i in range(2)
        ]
        svc = SolverService(
            cache=ExecutableCache(manifest_path=None), batch_max=8,
            batch_window_s=0.001, dim_floor=16, nrhs_floor=4,
            factor_cache=False,  # tail latency of the DIRECT bucket path
        )
        keys = {
            n: _bk.bucket_for("gesv", n, n, 4, np.float64,
                              floor=16, nrhs_floor=4)
            for n in (nsm, nlg)
        }
        for k in keys.values():
            svc.cache.ensure_manifest(k, (1, 8))
        svc.warmup()
        t0 = time.perf_counter()
        with _m.deltas() as d:
            futs = [
                # 3:1 small:large mix, interleaved so buckets contend
                svc.submit("gesv", *probs[i % len(probs)])
                for i in range(reqs)
            ]
            for f in futs:
                assert np.all(np.isfinite(f.result(timeout=600)))
        dt = time.perf_counter() - t0
        svc.stop()
        out = {"requests": reqs,
               "requests_per_s": round(reqs / dt, 1),
               "seconds": round(dt, 3)}
        for n, k in keys.items():
            row = {}
            for split in ("queued", "execute", "total"):
                h = d.hist(f"serve.latency.{k.label}.{split}")
                if h:
                    row[split] = {
                        "p50_ms": round(h["p50"] * 1e3, 2),
                        "p95_ms": round(h["p95"] * 1e3, 2),
                        "p99_ms": round(h["p99"] * 1e3, 2),
                    }
            row["count"] = (d.hist(f"serve.latency.{k.label}.total")
                            or {}).get("count", 0)
            out[f"n{n}"] = row
        return out

    run_entry("serve_latency", entry_serve_latency)

    # -- factor-once solve-many: a warmed repeated-A stream (1 factor +
    # N right-hand sides) through the factor cache's trsm-only solve
    # buckets vs the same stream refactoring every request.  The
    # headline is speedup_vs_refactor: steady-state O(n^2) vs O(n^3)
    # per request (the hit/miss deltas prove which path served) -------
    def entry_factor_solve_many():
        from slate_tpu.aux import metrics as _m
        from slate_tpu.serve.cache import ExecutableCache
        from slate_tpu.serve.factor_cache import FactorCache
        from slate_tpu.serve.service import SolverService

        nfc = 1024 if on_tpu else 128
        reqs = 32
        rng = np.random.default_rng(0)
        A = rng.standard_normal((nfc, nfc)) + nfc * np.eye(nfc)
        Bs = [rng.standard_normal((nfc, 4)) for _ in range(8)]
        out = {"n": nfc, "requests": reqs}
        rates = {}
        for mode in ("refactor", "factor_cache"):
            # False = explicitly off (None would re-resolve the
            # SLATE_TPU_FACTOR_CACHE env and poison the baseline)
            fc = FactorCache(max_entries=8) if mode == "factor_cache" \
                else False
            svc = SolverService(
                cache=ExecutableCache(manifest_path=None), batch_max=8,
                batch_window_s=0.001, factor_cache=fc,
            )
            # warm: one solve registers (and, with the cache, factors);
            # warmup() then precompiles the registered buckets so the
            # measured stream is compile-free on both paths
            svc.submit("gesv", A, Bs[0]).result(timeout=600)
            svc.warmup()
            t0 = time.perf_counter()
            with _m.deltas() as d:
                futs = [
                    svc.submit("gesv", A, Bs[i % len(Bs)])
                    for i in range(reqs)
                ]
                for f in futs:
                    assert np.all(np.isfinite(f.result(timeout=600)))
                hits = int(d.get("serve.factor_cache.hit"))
                misses = int(d.get("serve.factor_cache.miss"))
            dt = time.perf_counter() - t0
            svc.stop()
            rates[mode] = reqs / dt
            out[mode] = {
                "requests_per_s": round(reqs / dt, 1),
                "seconds": round(dt, 3),
                "hits": hits,
                "misses": misses,
            }
        out["speedup_vs_refactor"] = round(
            rates["factor_cache"] / max(rates["refactor"], 1e-9), 2
        )
        return out

    run_entry("factor_solve_many", entry_factor_solve_many)

    # -- gels factor reuse (fabric/): a warmed repeated-A least-squares
    # stream through the QR-pack solve buckets + device arena vs the
    # same stream refactoring every request.  speedup_vs_refactor is
    # the tentpole headline (steady-state O(m n nrhs) vs O(m n^2) per
    # request); top-level requests_per_s carries the floor ------------
    def entry_gels_factor_reuse():
        from slate_tpu.aux import metrics as _m
        from slate_tpu.fabric.arena import FactorArena
        from slate_tpu.serve.cache import ExecutableCache
        from slate_tpu.serve.factor_cache import FactorCache
        from slate_tpu.serve.service import SolverService

        ng = 512 if on_tpu else 96
        mg = 2 * ng
        reqs = 24
        rng = np.random.default_rng(0)
        A = rng.standard_normal((mg, ng))
        Bs = [rng.standard_normal((mg, 4)) for _ in range(8)]
        out = {"m": mg, "n": ng, "requests": reqs}
        rates = {}
        for mode in ("refactor", "fabric"):
            # False = explicitly off (None would re-resolve the env
            # and poison the refactor baseline)
            fabric = mode == "fabric"
            svc = SolverService(
                cache=ExecutableCache(manifest_path=None), batch_max=8,
                batch_window_s=0.001,
                factor_cache=FactorCache(max_entries=8) if fabric
                else False,
                factor_arena=FactorArena() if fabric else False,
            )
            svc.submit("gels", A, Bs[0]).result(timeout=600)
            svc.warmup()  # precompile the registered buckets
            t0 = time.perf_counter()
            with _m.deltas() as d:
                futs = [
                    svc.submit("gels", A, Bs[i % len(Bs)])
                    for i in range(reqs)
                ]
                for f in futs:
                    assert np.all(np.isfinite(f.result(timeout=600)))
                hits = int(d.get("serve.factor_cache.hit") or 0)
                avoided = int(
                    d.get("serve.arena.upload_avoided_bytes") or 0
                )
            dt = time.perf_counter() - t0
            svc.stop()
            rates[mode] = reqs / dt
            out[mode] = {
                "requests_per_s": round(reqs / dt, 1),
                "seconds": round(dt, 3),
                "hits": hits,
            }
            if fabric:
                out[mode]["upload_avoided_bytes"] = avoided
        out["requests_per_s"] = round(rates["fabric"], 1)
        out["speedup_vs_refactor"] = round(
            rates["fabric"] / max(rates["refactor"], 1e-9), 2
        )
        return out

    run_entry("gels_factor_reuse", entry_gels_factor_reuse)

    # -- streaming session updates (fabric/session.py): append k rows,
    # O(k n^2) Householder fold into R, fenced CSNE solve — vs a full
    # refactor (lstsq) per step on the grown A.  requests_per_s counts
    # streamed solves (the floored headline); speedup_vs_refactor is
    # informational — at the tiny CPU shapes the python-loop update is
    # slower than LAPACK's refactor, the asymptotics only win at real
    # sizes.  Parity is asserted every step ---------------------------
    def entry_session_stream_update():
        from slate_tpu.fabric.session import FactorSession
        from slate_tpu.serve.cache import ExecutableCache
        from slate_tpu.serve.service import SolverService

        ns = 256 if on_tpu else 64
        m0 = 2 * ns
        steps, k = 8, 4
        rng = np.random.default_rng(0)
        A0 = rng.standard_normal((m0, ns))
        Cs = [rng.standard_normal((k, ns)) for _ in range(steps)]
        bs = [
            rng.standard_normal((m0 + (i + 1) * k, 2))
            for i in range(steps)
        ]
        svc = SolverService(
            cache=ExecutableCache(manifest_path=None), batch_max=4,
            batch_window_s=0.001, factor_cache=False,
        )
        sess = FactorSession(svc, A0)
        Xs = []
        t0 = time.perf_counter()
        for C, b in zip(Cs, bs):
            sess.append(C)
            Xs.append(sess.solve(b))
        dt_s = time.perf_counter() - t0
        svc.stop()
        A_cur = A0
        t0 = time.perf_counter()
        refs = []
        for C, b in zip(Cs, bs):
            A_cur = np.vstack([A_cur, C])
            refs.append(np.linalg.lstsq(A_cur, b, rcond=None)[0])
        dt_r = time.perf_counter() - t0
        err = max(
            float(np.abs(x - r).max()) for x, r in zip(Xs, refs)
        )
        assert err < 1e-8, f"streamed update drifted: {err}"
        return {
            "m0": m0, "n": ns, "steps": steps, "rows_per_step": k,
            "requests_per_s": round(steps / dt_s, 1),
            "seconds": round(dt_s, 3),
            "refactor_seconds": round(dt_r, 3),
            "speedup_vs_refactor": round(dt_r / max(dt_s, 1e-9), 2),
            "max_err": err,
        }

    run_entry("session_stream_update", entry_session_stream_update)

    # -- multi-tenant fairness: the SAME burst trace (one abusive
    # flood, then a well-behaved tenant's small stream) through a
    # static config vs the admission plane (tenant quotas + WFQ +
    # adaptive window).  The headline is the well-behaved tenant's p99
    # under each config plus the abuser's shed/rejected counts — on
    # CPU the queueing deltas are modest (one worker, fast solves);
    # the curve is for real chips, the fairness direction holds
    # everywhere ------------------------------------------------------
    def entry_serve_multitenant():
        from slate_tpu.aux import metrics as _m
        from slate_tpu.serve import buckets as _bk
        from slate_tpu.serve.cache import ExecutableCache
        from slate_tpu.serve.service import SolverService
        from slate_tpu.exceptions import SlateError

        n_ab = 1024 if on_tpu else 192
        n_good = 512 if on_tpu else 96
        flood, nice = 24, 8
        rng = np.random.default_rng(0)
        A_a = rng.standard_normal((n_ab, n_ab)) + n_ab * np.eye(n_ab)
        B_a = rng.standard_normal((n_ab, 4))
        good_probs = [
            (rng.standard_normal((n_good, n_good))
             + n_good * np.eye(n_good),
             rng.standard_normal((n_good, 4)))
            for _ in range(nice)
        ]
        k_ab = _bk.bucket_for("gesv", n_ab, n_ab, 4, np.float64)
        k_good = _bk.bucket_for("gesv", n_good, n_good, 4, np.float64)
        out = {"n_abuser": n_ab, "n_good": n_good,
               "flood": flood, "good_requests": nice}
        for mode in ("static", "adaptive"):
            # tenants=""/adaptive=False: explicitly OFF for the static
            # baseline (None would re-resolve SLATE_TPU_TENANTS/
            # SLATE_TPU_ADAPTIVE and poison the comparison — the same
            # trap factor_cache=False guards against above)
            kw = dict(
                cache=ExecutableCache(manifest_path=None), batch_max=4,
                batch_window_s=0.002, factor_cache=False,
                tenants="", adaptive=False,
            )
            if mode == "adaptive":
                kw.update(
                    tenants=(
                        "good:weight=4;"
                        "abuser:rate=10,burst=4,share=0.25"
                    ),
                    adaptive=True, latency_budget_s=0.25,
                )
            svc = SolverService(**kw)
            svc.cache.ensure_manifest(k_ab, (1, 4))
            svc.cache.ensure_manifest(k_good, (1, 4))
            svc.warmup()  # the burst measures queueing, not compiles
            refused = 0
            t0 = time.perf_counter()
            with _m.deltas() as d:
                futs = []
                for _ in range(flood):
                    try:
                        futs.append(svc.submit(
                            "gesv", A_a, B_a, tenant="abuser",
                            priority="low",
                        ))
                    except SlateError:
                        refused += 1  # quota/share Rejected or Shed
                for A, B in good_probs:
                    futs.append(svc.submit(
                        "gesv", A, B, tenant="good", priority="high",
                    ))
                for f in futs:
                    assert np.all(np.isfinite(f.result(timeout=600)))
            dt = time.perf_counter() - t0
            svc.stop()
            # the victim's p99: per-tenant histogram when the plane is
            # on, the good bucket's histogram for the static baseline
            # (same requests — the abuser rides a different bucket)
            h = d.hist(
                "serve.latency.tenant.good.total" if mode == "adaptive"
                else f"serve.latency.{k_good.label}.total"
            )
            out[mode] = {
                "seconds": round(dt, 3),
                "good_p99_ms": (
                    round(h["p99"] * 1e3, 2) if h else None
                ),
                "abuser_refused": refused,
                "shed": int(d.get("serve.shed")),
                "rejected_quota": int(d.get("serve.rejected_quota")),
            }
        return out

    run_entry("serve_multitenant", entry_serve_multitenant)

    # -- sustained soak throughput: the soak fabric's open-loop replay
    # (multitenant + repeated-A mix, all serve planes armed) through a
    # warm service.  The headline is delivered req/s at the offered
    # rate's ceiling plus the client-observed p99 — the number the
    # --soak gate budgets against, tracked here so regressions show up
    # in bench_diff before they show up as a red gate ------------------
    def entry_soak_sustained():
        from slate_tpu.aux import metrics as _m
        from slate_tpu.serve import buckets as _bk
        from slate_tpu.serve.cache import ExecutableCache
        from slate_tpu.serve.factor_cache import FactorCache
        from slate_tpu.serve.service import SolverService
        from slate_tpu.soak import replay as _rp

        reqs = 4000 if on_tpu else 1200
        svc = SolverService(
            cache=ExecutableCache(manifest_path=None), batch_max=8,
            batch_window_s=0.001, dim_floor=16, nrhs_floor=4,
            factor_cache=FactorCache(max_entries=32),
            tenants="gold:weight=4;good:weight=2;free:rate=400,share=0.5",
            adaptive=True, latency_budget_s=0.5,
        )
        try:
            for routine, n in (("gesv", 12), ("posv", 12), ("gesv", 24)):
                k = _bk.bucket_for(routine, n, n, 2, np.float64,
                                   floor=16, nrhs_floor=4)
                svc.cache.ensure_manifest(k, (1, 8))
                svc.cache.ensure_manifest(k.solve_sibling(), (1, 8))
            svc.warmup()
            spec = _rp.merge_specs(
                _rp.gen_multitenant(reqs // 2, seed=1, rate_rps=500.0),
                _rp.gen_repeated_a(reqs // 2, seed=2, rate_rps=500.0,
                                   distinct=8),
            )
            # factor the pools before measuring: steady-state numbers,
            # not cold-cache numbers (the --soak gate does the same)
            _rp.replay(svc, _rp.warm_spec(spec, gap_s=0.01), speed=1.0,
                       seed=0, check_results=False)
            with _m.deltas() as d:
                res = _rp.replay(svc, spec, speed=4.0, seed=0,
                                 check_results=False)
                compiles = int(d.get("jit.compilations"))
        finally:
            svc.stop()
        return {
            "requests": res["submitted"],
            "delivered": res["delivered"],
            "refused": res["refused"],
            "requests_per_s": round(res["requests_per_s"], 1),
            "p50_s": res["p50_s"], "p99_s": res["p99_s"],
            "seconds": round(res["wall_s"], 3),
            "steady_compiles": compiles,
        }

    run_entry("soak_sustained", entry_soak_sustained)

    # -- two-stage heev values (he2hb + bulge chase + bisection) ----------
    nh = 1024 if on_tpu else 96

    def entry_heev_values():
        rep, sec = bench_heev_values(jax, jnp, nh, 64 if on_tpu else 8,
                                     max(2, trials - 3))
        return {"n": nh, **rep, "seconds": round(sec, 3)}

    run_entry("dheev_values_two_stage", entry_heev_values)

    # -- two-stage heev with vectors (+ native stedc D&C) -----------------
    def entry_heev_vectors():
        rep, sec = bench_heev_vectors(jax, jnp, nh, 64 if on_tpu else 8,
                                      max(2, trials - 3))
        return {"n": nh, **rep, "seconds": round(sec, 3)}

    run_entry("dheev_vectors_two_stage", entry_heev_vectors)

    # -- large-n heev with vectors, stage-split (the flagship path;
    # machine-readable stage seconds — verdict r4 weak #5) ---------------
    if on_tpu:
        import slate_tpu as st
        from slate_tpu.drivers.eig import heev_staged

        def entry_heev_staged(nbig):
            key = jax.random.PRNGKey(5)
            G = jax.random.normal(key, (nbig, nbig), jnp.float64)
            S = (G + G.T) / 2
            Ah = st.HermitianMatrix.from_global(S, 128, uplo=st.Uplo.Lower)
            heev_staged(Ah, vectors=True)  # compile + warm
            Ah2 = Ah._with(data=Ah.data + 1e-14)
            t0 = time.perf_counter()
            w, Z, stage_t = heev_staged(Ah2, vectors=True)
            sec = time.perf_counter() - t0
            return {
                "n": nbig, "seconds": round(sec, 2),
                # staged path compiles per stage — no single cost record
                # covers the chain, so this one stays on the hand model
                "gflops": round(20.0 * nbig**3 / 3.0 / sec / 1e9, 1),
                "flops_source": "model",
                "stages": stage_t,
            }

        for nbig in (2048, 4096, 8192) if args.full else (2048, 4096):
            run_entry(f"dheev_vectors_staged_n{nbig}",
                      lambda nbig=nbig: entry_heev_staged(nbig))

    _progress("metrics summary\n" + metrics.report())
    if os.environ.get("SLATE_TPU_METRICS"):
        metrics.dump()

    baseline_gflops = 700.0  # reference dgemm per GPU (docs/usage.md:40-42)
    # sweep-wide waste ratio from the new factor.* counter pair: executed
    # vs model FLOPs across every factorization the sweep dispatched
    # (None when no factorization entry ran — the field always prints)
    fmodel = metrics.counters().get("factor.flops_model", 0.0)
    fexec = metrics.counters().get("factor.flops_exec", 0.0)
    waste = round(fexec / fmodel, 3) if fmodel > 0 else None
    print(
        json.dumps(
            {
                "metric": f"sgemm_n{n}_gflops_per_chip",
                "value": round(gf_fast, 1),
                "unit": "GFLOP/s",
                "vs_baseline": round(gf_fast / baseline_gflops, 3),
                "flops_waste_ratio": waste,
                "extra": extra,
            }
        )
    )


if __name__ == "__main__":
    main()
