#!/usr/bin/env python
"""Headline benchmark: single-chip large gemm through the slate_tpu driver.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline: the reference's only published figure is dgemm at 0.70 TFLOP/s
per GPU (4 ranks, GPU-aware MPI; reference docs/usage.md:40-42, see
BASELINE.md).  vs_baseline = our GFLOP/s per chip / 700.

Runs on whatever accelerator jax exposes (the axon TPU chip under the
driver; CPU elsewhere).  f32: the TPU MXU's native precision class — the
reference's f64 runs on GPUs with native f64 units, the TPU analogue is
f32 (see SURVEY §7 hard-part (5)).
"""

import json
import time

import jax
import jax.numpy as jnp
import numpy as np


def main():
    on_tpu = any(d.platform != "cpu" for d in jax.devices())
    n = 8192 if on_tpu else 512
    nb = 1024 if on_tpu else 128
    dtype = jnp.float32

    from slate_tpu.drivers import blas3
    from slate_tpu.matrix.matrix import Matrix

    key = jax.random.PRNGKey(0)
    ka, kb = jax.random.split(key)
    A2 = jax.random.normal(ka, (n, n), dtype)
    B2 = jax.random.normal(kb, (n, n), dtype) * (1.0 / n)

    A = Matrix.from_global(A2, nb)
    B = Matrix.from_global(B2, nb)

    # Chain K dependent gemms inside ONE jit call: per-call dispatch over
    # the device tunnel is ~100ms, so the timed region must amortize it,
    # and chaining defeats any result caching of repeated identical calls.
    K = 8 if on_tpu else 3

    @jax.jit
    def step(A, B, t):
        # t varies per trial so no layer of the stack can serve a cached
        # result for a repeated identical invocation
        C = A._with(data=A.data + t)
        for _ in range(K):
            C = blas3.gemm(1.0, C, B, 0.0, C)
        return C.data.sum()  # scalar readback forces real execution

    float(step(A, B, 0.0))  # compile + warmup

    best = float("inf")
    for trial in range(5 if on_tpu else 2):
        t0 = time.perf_counter()
        s = float(step(A, B, 1.0 + trial))  # host readback = hard barrier
        best = min(best, time.perf_counter() - t0)
    assert np.isfinite(s)

    gflops = 2.0 * n * n * n * K / best / 1e9
    baseline_gflops = 700.0  # reference dgemm per GPU (docs/usage.md:40-42)
    print(
        json.dumps(
            {
                "metric": f"sgemm_n{n}_gflops_per_chip",
                "value": round(gflops, 1),
                "unit": "GFLOP/s",
                "vs_baseline": round(gflops / baseline_gflops, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
