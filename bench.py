#!/usr/bin/env python
"""Headline benchmark sweep over the driver stack on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "extra"}.

Headline metric: sgemm GFLOP/s per chip in the single-pass MXU mode
(SLATE_TPU_FAST_F32, the mode BENCH_r01 measured).  Baseline: the
reference's only published figure, dgemm 0.70 TFLOP/s per GPU (reference
docs/usage.md:40-42; see BASELINE.md).  vs_baseline = GFLOP/s / 700.

"extra" carries the north-star routine entries (BASELINE.json asks for
gemm/potrf/getrf/geqrf/heev): dgemm + f64 factorizations + the two-stage
heev values path, each with GFLOP/s and seconds.  f32 accurate-mode gemm
(the product default after the precision policy) is reported alongside
the fast mode.  See BENCH_NOTES.md for methodology and regression notes.
"""

import json
import os
import time

import numpy as np

# Persistent XLA compilation cache: the native blocked factorization
# kernels compile in minutes over this toolchain the first time; cached
# executables load in seconds on every later run.
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.expanduser("~"), ".cache", "jax_comp"),
)
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1.0")


def _bench(step_fn, warm_args, trials):
    """Best-of wall time with host readback as the barrier."""
    float(step_fn(*warm_args, 0.0))  # compile + warmup
    best = float("inf")
    for trial in range(trials):
        t0 = time.perf_counter()
        s = float(step_fn(*warm_args, 1.0 + trial))
        best = min(best, time.perf_counter() - t0)
        assert np.isfinite(s)
    return best


def bench_gemm(jax, jnp, n, nb, dtype, K, trials):
    from slate_tpu.drivers import blas3
    from slate_tpu.matrix.matrix import Matrix

    key = jax.random.PRNGKey(0)
    ka, kb = jax.random.split(key)
    A = Matrix.from_global(jax.random.normal(ka, (n, n), dtype), nb)
    B = Matrix.from_global(jax.random.normal(kb, (n, n), dtype) * (1.0 / n), nb)

    @jax.jit
    def step(A, B, t):
        # t varies per trial so no layer can serve a cached result; the
        # K-chain amortizes per-dispatch tunnel latency (~100ms)
        C = A._with(data=A.data + t)
        for _ in range(K):
            C = blas3.gemm(1.0, C, B, 0.0, C)
        return C.data.sum()

    best = _bench(step, (A, B), trials)
    return 2.0 * n**3 * K / best / 1e9, best / K


def bench_potrf(jax, jnp, n, nb, trials):
    import slate_tpu as st

    key = jax.random.PRNGKey(1)
    G = jax.random.normal(key, (n, n), jnp.float64) / np.sqrt(n)
    S = G @ G.T + 2.0 * jnp.eye(n, dtype=jnp.float64)
    A = st.HermitianMatrix.from_global(S, nb, uplo=st.Uplo.Lower)

    @jax.jit
    def step(A, t):
        L, info = st.potrf(A._with(data=A.data + t * 1e-14))
        return L.data.sum() + info

    best = _bench(step, (A,), trials)
    return n**3 / 3.0 / best / 1e9, best


def bench_getrf(jax, jnp, n, nb, trials):
    import slate_tpu as st

    key = jax.random.PRNGKey(2)
    G = jax.random.normal(key, (n, n), jnp.float64)
    A = st.Matrix.from_global(G + n * jnp.eye(n, dtype=jnp.float64), nb)

    @jax.jit
    def step(A, t):
        LU, piv, info = st.getrf(A._with(data=A.data + t * 1e-14))
        return LU.data.sum() + info

    best = _bench(step, (A,), trials)
    return 2.0 * n**3 / 3.0 / best / 1e9, best


def bench_geqrf(jax, jnp, n, nb, trials):
    import slate_tpu as st

    key = jax.random.PRNGKey(3)
    A = st.Matrix.from_global(jax.random.normal(key, (n, n), jnp.float64), nb)

    @jax.jit
    def step(A, t):
        fac, T = st.geqrf(A._with(data=A.data + t * 1e-14))
        return fac.data.sum()

    best = _bench(step, (A,), trials)
    return 4.0 * n**3 / 3.0 / best / 1e9, best


def bench_heev_vectors(jax, jnp, n, nb, trials):
    """Two-stage heev WITH eigenvectors: he2hb + hb2st wavefront +
    native stedc divide & conquer + both back-transforms — no vendor
    eigensolver anywhere on the path (the vendor f64 eigh is a compile
    bomb past n~512 on this toolchain)."""
    import slate_tpu as st

    key = jax.random.PRNGKey(4)
    G = jax.random.normal(key, (n, n), jnp.float64)
    S = (G + G.T) / 2
    A = st.HermitianMatrix.from_global(S, nb, uplo=st.Uplo.Lower)

    @jax.jit
    def step(A, t):
        w, Z = st.heev(A._with(data=A.data + t * 1e-14), vectors=True)
        return w.sum() + Z.data.ravel()[-1]

    best = _bench(step, (A,), trials)
    # flop model for the WITH-vectors path: 4n^3/3 reduction + ~4n^3/3
    # D&C vector assembly + 2n^3 hb2st back-transform + 2n^3 he2hb
    # back-transform ~= 20n^3/3 (LAPACK dsyevd-style accounting), so the
    # rate is comparable across entries (ADVICE r3)
    return 20.0 * n**3 / 3.0 / best / 1e9, best


def bench_heev_values(jax, jnp, n, nb, trials):
    """Two-stage heev, eigenvalues only: he2hb + hb2st wavefront +
    Sturm bisection — no vendor eigensolver anywhere on this path."""
    import slate_tpu as st

    key = jax.random.PRNGKey(4)
    G = jax.random.normal(key, (n, n), jnp.float64)
    S = (G + G.T) / 2
    A = st.HermitianMatrix.from_global(S, nb, uplo=st.Uplo.Lower)

    @jax.jit
    def step(A, t):
        w, _ = st.heev(A._with(data=A.data + t * 1e-14), vectors=False)
        return w.sum()

    best = _bench(step, (A,), trials)
    return 4.0 * n**3 / 3.0 / best / 1e9, best


def _progress(msg):
    """Stage marker on stderr (the JSON contract owns stdout): makes a
    wedged remote compile attributable from the driver's log."""
    import sys

    print(f"[bench] {msg}", file=sys.stderr, flush=True)


def main():
    import jax

    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp

    on_tpu = any(d.platform != "cpu" for d in jax.devices())
    trials = 5 if on_tpu else 2
    extra = {}

    # -- headline: fast-f32 sgemm (BENCH_r01's mode) ----------------------
    _progress("sgemm fast-f32")
    os.environ["SLATE_TPU_FAST_F32"] = "1"
    n = 8192 if on_tpu else 512
    gf_fast, sec = bench_gemm(jax, jnp, n, 1024 if on_tpu else 128,
                              jnp.float32, 8 if on_tpu else 2, trials)
    extra["sgemm_fast_f32"] = {"n": n, "gflops": round(gf_fast, 1)}

    # -- accurate-mode f32 gemm (product default) -------------------------
    _progress("sgemm accurate")
    os.environ["SLATE_TPU_FAST_F32"] = "0"
    gf_acc, _ = bench_gemm(jax, jnp, n, 1024 if on_tpu else 128,
                           jnp.float32, 4 if on_tpu else 2, trials)
    extra["sgemm_accurate"] = {"n": n, "gflops": round(gf_acc, 1)}

    # -- dgemm (the north-star dtype).  n stays 4096: the n=8192 f64
    # chain compile wedges the tunnel's remote-compile service (>2 h,
    # host idle); the honest n=8192 denominator (1,927 GF/s) is
    # measured out-of-band by tools/profile_factor.py and recorded in
    # BENCH_NOTES.md's ceiling analysis
    _progress("dgemm f64")
    nd = 4096 if on_tpu else 256
    gf_d, _ = bench_gemm(jax, jnp, nd, 512 if on_tpu else 128,
                         jnp.float64, 4 if on_tpu else 2, trials)
    extra["dgemm"] = {"n": nd, "gflops": round(gf_d, 1)}

    # -- f64 factorizations ------------------------------------------------
    _progress("dpotrf")
    nf = 8192 if on_tpu else 256
    gf, sec = bench_potrf(jax, jnp, nf, 512 if on_tpu else 64, trials)
    extra["dpotrf"] = {"n": nf, "gflops": round(gf, 1), "seconds": round(sec, 3)}
    _progress("dgetrf")
    nl = 8192 if on_tpu else 128
    gf, sec = bench_getrf(jax, jnp, nl, 512 if on_tpu else 32, trials)
    extra["dgetrf"] = {"n": nl, "gflops": round(gf, 1), "seconds": round(sec, 3)}
    _progress("dgeqrf")
    nq = 8192 if on_tpu else 128
    gf, sec = bench_geqrf(jax, jnp, nq, 512 if on_tpu else 32, trials)
    extra["dgeqrf"] = {"n": nq, "gflops": round(gf, 1), "seconds": round(sec, 3)}

    # -- two-stage heev values (he2hb + bulge chase + bisection) ----------
    _progress("heev values")
    nh = 1024 if on_tpu else 96
    try:
        gf, sec = bench_heev_values(jax, jnp, nh, 64 if on_tpu else 8,
                                    max(2, trials - 3))
        extra["dheev_values_two_stage"] = {
            "n": nh, "gflops": round(gf, 1), "seconds": round(sec, 3)
        }
    except Exception as e:  # noqa: BLE001 — bench must still emit its line
        extra["dheev_values_two_stage"] = {"error": str(e)[:120]}

    # -- two-stage heev with vectors (+ native stedc D&C) -----------------
    _progress("heev vectors")
    nv = 1024 if on_tpu else 96
    try:
        gf, sec = bench_heev_vectors(jax, jnp, nv, 64 if on_tpu else 8,
                                     max(2, trials - 3))
        extra["dheev_vectors_two_stage"] = {
            "n": nv, "gflops": round(gf, 1), "seconds": round(sec, 3)
        }
    except Exception as e:  # noqa: BLE001 — bench must still emit its line
        extra["dheev_vectors_two_stage"] = {"error": str(e)[:120]}

    # -- large-n heev with vectors, stage-split (the flagship path;
    # machine-readable stage seconds — verdict r4 weak #5) ---------------
    if on_tpu:
        import slate_tpu as st
        from slate_tpu.drivers.eig import heev_staged

        for nbig in (2048, 4096, 8192):
            _progress(f"heev staged n={nbig}")
            try:
                key = jax.random.PRNGKey(5)
                G = jax.random.normal(key, (nbig, nbig), jnp.float64)
                S = (G + G.T) / 2
                Ah = st.HermitianMatrix.from_global(
                    S, 128, uplo=st.Uplo.Lower
                )
                heev_staged(Ah, vectors=True)  # compile + warm
                Ah2 = Ah._with(data=Ah.data + 1e-14)
                t0 = time.perf_counter()
                w, Z, stage_t = heev_staged(Ah2, vectors=True)
                sec = time.perf_counter() - t0
                extra[f"dheev_vectors_staged_n{nbig}"] = {
                    "n": nbig, "seconds": round(sec, 2),
                    "gflops": round(20.0 * nbig**3 / 3.0 / sec / 1e9, 1),
                    "stages": stage_t,
                }
            except Exception as e:  # noqa: BLE001
                extra[f"dheev_vectors_staged_n{nbig}"] = {
                    "error": str(e)[:120]
                }

    baseline_gflops = 700.0  # reference dgemm per GPU (docs/usage.md:40-42)
    print(
        json.dumps(
            {
                "metric": f"sgemm_n{n}_gflops_per_chip",
                "value": round(gf_fast, 1),
                "unit": "GFLOP/s",
                "vs_baseline": round(gf_fast / baseline_gflops, 3),
                "extra": extra,
            }
        )
    )


if __name__ == "__main__":
    main()
