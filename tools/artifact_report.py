#!/usr/bin/env python
"""Per-bucket artifact-store outcome table from a metrics JSONL.

    python tools/artifact_report.py out.jsonl

Rows come from the ``serve.artifact.<bucket>.b<batch>.<outcome>``
counters the artifact store emits on every load
(slate_tpu/serve/artifacts.py): ``hit`` (verified export artifact
deserialized — zero retrace/compile), ``miss`` (nothing persisted),
``stale`` (fingerprint drift: different jaxlib/device/x64/schedule),
``corrupt`` (checksum or header verification failed), ``load_fail``
(verified bytes failed to deserialize), ``cache_seed`` (recompile rung
warmed by the persistent XLA cache).

Exit status is the **integrity verdict**: when fault injection is on
(``SLATE_TPU_FAULTS`` with the ``artifact_corrupt`` /
``artifact_stale`` / ``artifact_load_fail`` sites), every injected
fault must show up in the matching detection counter — an injected
corruption that no verification rung caught means a corrupt artifact
was *loaded unverified*, and the report exits nonzero.  That is the
``run_tests.py --coldstart`` chaos gate.

Produce the JSONL with ``SLATE_TPU_METRICS=out.jsonl`` around any
serving workload with ``SLATE_TPU_ARTIFACTS`` set.
"""

import argparse
import json
import re
import sys

OUTCOMES = ("hit", "miss", "stale", "corrupt", "load_fail", "cache_seed")

_ROW_RE = re.compile(
    r"^serve\.artifact\.(?P<bucket>.+)\.b(?P<batch>\d+)"
    r"\.(?P<outcome>" + "|".join(OUTCOMES) + r")$"
)

#: placement suffix of a bucket label (buckets.BucketKey.label appends
#: ``.meshPxQ`` for spmd-sharded executables — those entries always
#: take the cache_seed rung, keyed by their mesh shape)
_MESH_RE = re.compile(r"\.mesh(\d+x\d+)$")


def bucket_mesh(bucket):
    """The mesh column of one bucket label: "-" = single device."""
    m = _MESH_RE.search(bucket)
    return m.group(1) if m else "-"

#: fault site -> the detection counter that must absorb every injection
SITE_DETECTORS = {
    "artifact_corrupt": "serve.artifact_corrupt",
    "artifact_stale": "serve.artifact_stale",
    "artifact_load_fail": "serve.artifact_load_fail",
}


def load_counters(path):
    # counter rows are cumulative snapshots: last value wins (same
    # semantics as tools/chaos_report.py — summing would inflate any
    # JSONL that metrics.dump() wrote more than once into)
    out = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            r = json.loads(line)
            if r.get("type") == "counter":
                out[r["name"]] = r.get("value", 0)
    return out


def main(argv=None):
    ap = argparse.ArgumentParser(prog="artifact_report")
    ap.add_argument("jsonl", help="metrics JSONL (SLATE_TPU_METRICS output)")
    args = ap.parse_args(argv)

    counters = load_counters(args.jsonl)
    rows = {}
    for name, value in counters.items():
        m = _ROW_RE.match(name)
        if not m:
            continue
        key = (m.group("bucket"), int(m.group("batch")))
        rows.setdefault(key, dict.fromkeys(OUTCOMES, 0))
        rows[key][m.group("outcome")] += int(value)

    if rows:
        hdr = (f"{'bucket':44} {'batch':>5} {'mesh':>6} " + " ".join(
            f"{o:>10}" for o in OUTCOMES
        ))
        print(hdr)
        print("-" * len(hdr))
        for (bucket, batch), r in sorted(rows.items()):
            print(f"{bucket:44} {batch:5d} {bucket_mesh(bucket):>6} "
                  + " ".join(f"{r[o]:10d}" for o in OUTCOMES))
    else:
        print("(no serve.artifact.* counters in this JSONL — was "
              "SLATE_TPU_ARTIFACTS set?)")

    saved = int(counters.get("serve.artifact_saved", 0))
    if saved:
        print(f"\n{saved} artifact(s) saved this run "
              f"({int(counters.get('serve.artifact_saved_export', 0))} "
              f"export, "
              f"{int(counters.get('serve.artifact_saved_cache_seed', 0))} "
              f"cache_seed)"
              + (f", {int(counters.get('serve.artifact_save_error', 0))} "
                 f"save error(s)"
                 if counters.get("serve.artifact_save_error") else ""))

    # the integrity verdict: injected artifact faults vs detections
    rc = 0
    for site, detector in SITE_DETECTORS.items():
        injected = int(counters.get(f"faults.injected.{site}", 0))
        detected = int(counters.get(detector, 0))
        if injected == 0:
            continue
        verdict = "verified" if detected >= injected else "UNVERIFIED"
        print(f"{site}: injected={injected} detected={detected} "
              f"[{verdict}]")
        if detected < injected:
            # a corrupt/stale/unloadable artifact got past verification
            rc = 1
    if rc:
        print("FAIL: injected artifact faults escaped the integrity "
              "checks — a bad artifact was loaded unverified")
    return rc


if __name__ == "__main__":
    sys.exit(main())
