#!/usr/bin/env python
"""Stitch per-host Chrome span exports into one cross-process trace.

The fleet tier's observability is per-process by construction: the
router and every worker dump their own span rings
(``aux/spans.export_chrome``), so one request's chain — router admit ->
dispatch -> worker admit/queued/execute -> deliver — lands in N files
that no trace viewer joins.  This tool folds them into a single
Perfetto/chrome://tracing JSON keyed by the library's trace ids:

* every input keeps its own ``pid`` track (and its ``process_name``
  metadata row — the router labels worker dumps ``host<i>``); a pid
  collision across files (pid reuse after a respawn) is rekeyed to a
  fresh synthetic pid so tracks never merge silently.
* span/parent ids are namespaced per input (``<pid>:<sid>``): sids are
  per-process counters, so two hosts' ``3`` must not alias in the
  stitched view.  Parent links never cross a process, so namespacing
  per input keeps every edge intact.
* trace ids pass through untouched — they are minted process-unique
  (``t<pidhex>-<n>``, aux/spans.new_trace) and are the join key: click
  any ``args.trace`` in Perfetto to follow one request across hosts.

**Orphan cross-host chains.**  A trace id names its minting process
(the ``t<pidhex>-`` prefix — the router, for fleet requests).  A trace
whose events appear in the stitched set while its MINTING process
contributed none is an orphan: a worker executed part of a chain whose
root half is missing (router dump absent, or its ring overwrote the
root) — an observability hole the fleet gate treats as a failure.  The
count is printed on the summary line (``orphans=N``) and the exit code
is 2 when any exist, unless ``--allow-orphans`` (the drill records the
count into the ``fleet.trace_orphans`` gauge and lets
``tools/fleet_report.py`` judge it).  A host that died mid-request is
NOT an orphan — the router half still roots the chain.

Stdlib-only by contract (reports must work when the library itself is
broken).

Usage:
    python tools/trace_stitch.py router.trace.json host*.trace.json \\
        -o stitched.json
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional, Set


def _mint_pid(trace_id: str) -> Optional[int]:
    """The pid embedded in a library trace id (``t<pidhex>-<n>``), or
    None for foreign/legacy ids (which then can't be orphan-checked)."""
    if not isinstance(trace_id, str) or not trace_id.startswith("t"):
        return None
    head, sep, _ = trace_id[1:].partition("-")
    if not sep:
        return None
    try:
        return int(head, 16)
    except ValueError:
        return None


def stitch(paths: List[str]) -> dict:
    """Merge the exports; returns ``{"traceEvents": [...], "stats":
    {files, events, traces, cross, orphans, orphan_traces}}``."""
    events: List[dict] = []
    meta: List[dict] = []
    used_pids: Set[int] = set()
    file_pids: Set[int] = set()  # post-rekey pid per input, union
    trace_pids: Dict[str, Set[int]] = {}
    for path in paths:
        with open(path) as f:
            doc = json.load(f)
        rows = doc.get("traceEvents", doc if isinstance(doc, list) else [])
        # one export = one process = one pid (spans.export_chrome);
        # verify, then rekey on collision with an earlier input
        pids = {r.get("pid") for r in rows if r.get("pid") is not None}
        if len(pids) > 1:
            raise SystemExit(
                f"trace_stitch: {path}: {len(pids)} pids in one export "
                "— expected one process per dump"
            )
        pid = next(iter(pids), None)
        if pid is None:
            continue  # empty export (spans off on that host)
        new_pid = pid
        while new_pid in used_pids:
            new_pid += 1 << 22  # past linux pid_max: synthetic, unique
        used_pids.add(new_pid)
        file_pids.add(new_pid)
        for r in rows:
            r = dict(r)
            r["pid"] = new_pid
            if r.get("ph") == "M":
                meta.append(r)
                continue
            args = r.get("args")
            if args:
                args = dict(args)
                # namespace per-process span counters; trace ids are
                # already process-unique and join as-is
                for k in ("span", "parent"):
                    if k in args:
                        args[k] = f"{new_pid}:{args[k]}"
                r["args"] = args
                tr = args.get("trace")
                if tr is not None:
                    trace_pids.setdefault(tr, set()).add(new_pid)
            events.append(r)
    events.sort(key=lambda r: (r.get("pid", 0), r.get("ts", 0.0)))
    orphans = []
    cross = 0
    for tr, pids in trace_pids.items():
        if len(pids) > 1:
            cross += 1
        mint = _mint_pid(tr)
        if mint is None:
            continue
        # the minting pid may have been rekeyed — it collided only if
        # another file already claimed it, in which case the ORIGINAL
        # claimant is a different process and the check below is still
        # the right one for that pid value
        if mint not in pids:
            orphans.append(tr)
    return {
        "traceEvents": meta + events,
        "displayTimeUnit": "ms",
        "stats": {
            "files": len(paths),
            "events": len(events),
            "traces": len(trace_pids),
            "cross": cross,
            "orphans": len(orphans),
            "orphan_traces": sorted(orphans)[:32],
        },
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("traces", nargs="+",
                    help="per-process Chrome exports to stitch")
    ap.add_argument("-o", "--output", default=None,
                    help="write the stitched JSON here")
    ap.add_argument("--allow-orphans", action="store_true",
                    help="exit 0 even with orphan chains (the caller "
                         "judges the printed count)")
    args = ap.parse_args(argv)
    doc = stitch(args.traces)
    stats = doc.pop("stats")
    if args.output:
        with open(args.output, "w") as f:
            json.dump(doc, f)
    print(
        "TRACE_STITCH files={files} events={events} traces={traces} "
        "cross={cross} orphans={orphans}".format(**stats)
    )
    for tr in stats["orphan_traces"]:
        print(f"  orphan trace {tr}: no events from its minting process")
    if stats["orphans"] and not args.allow_orphans:
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
