#!/usr/bin/env python
"""race-report: judge a sync-runtime dump, or check the static
lock-order graph against the checked-in artifact.

    python tools/race_report.py /tmp/sync.json       # judge a stress run
    python tools/race_report.py --check-graph        # artifact freshness

Dump mode reads the JSON ``aux/sync.dump()`` wrote after an
instrumented stress run (``SLATE_TPU_SYNC_CHECK=1`` — see the README
"Race & deadlock detection" section): it prints every violation with
both stacks (the two halves of a lock-order inversion, or the two
unordered accesses of an unguarded field) plus the observed acquisition
edges, and exits nonzero when ANY violation was recorded — the
``run_tests.py --race`` gate runs it over the clean serve stress leg
(must exit 0) and over the two planted-fixture legs (must exit
nonzero; a verdict tool that cannot fail proves nothing).

``--check-graph`` recomputes the static lock-order graph
(``slate_tpu/analysis/races.py``) and compares it with the checked-in
``LOCK_ORDER.json``: exits nonzero on a cycle, a new edge, a stale
artifact edge, or a missing artifact.  Regenerate after review with
``tools/slate_lint.py --write-lock-graph``.

Stdlib-only, loads the analysis package by file path (the slate_lint
pattern), so the verdict survives an import-broken library tree.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_analysis():
    """slate_tpu/analysis without executing slate_tpu/__init__ (which
    imports jax) — shared spelling with tools/slate_lint.py."""
    name = "slate_lint_analysis"
    if name in sys.modules:
        return sys.modules[name]
    pkg_dir = os.path.join(_ROOT, "slate_tpu", "analysis")
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(pkg_dir, "__init__.py"),
        submodule_search_locations=[pkg_dir],
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


def _indent(stack: str, pad: str = "    | ") -> str:
    return "\n".join(pad + ln for ln in (stack or "<no stack>").splitlines())


def judge_dump(path: str, verbose: bool = True) -> int:
    with open(path, encoding="utf-8") as fh:
        doc = json.load(fh)
    violations = doc.get("violations", [])
    edges = doc.get("edges", [])
    print(
        f"race-report: {len(violations)} violation(s), "
        f"{len(edges)} observed lock-order edge(s), "
        f"{doc.get('fields', 0)} probed field(s) "
        f"(seed={doc.get('seed')}, yield_p={doc.get('yield_p')})"
    )
    for i, v in enumerate(violations, 1):
        kind = v.get("kind", "?")
        print(f"\n[{i}] {kind}: {v.get('detail', '')}")
        stacks = v.get("stacks", [])
        labels = (
            ("first ordering established at", "inverted at")
            if kind == "lock_order"
            else ("previous access", "conflicting access")
        )
        for label, stack in zip(labels, stacks):
            print(f"  {label}:")
            if verbose:
                print(_indent(stack))
    if violations:
        print(
            "\nrace-report: FAIL — re-run the stress leg with the same "
            f"SLATE_TPU_SYNC_CHECK spec (seed={doc.get('seed')}) to "
            "replay the schedule"
        )
        return 1
    print("race-report: clean")
    return 0


def check_graph(root: str) -> int:
    analysis = _load_analysis()
    races = analysis.races
    loaded = analysis.core.load_project(root)
    edges = races.lock_graph(loaded.project)
    cycles = races.graph_cycles(edges)
    rc = 0
    for comp in cycles:
        print(f"race-report: lock-order CYCLE: {' <-> '.join(comp)}")
        rc = 1
    known = races.load_graph_artifact(root)
    if known is None:
        print(
            f"race-report: no {races.LOCK_GRAPH_NAME} at the repo root "
            "— generate it with tools/slate_lint.py --write-lock-graph"
        )
        return 1
    cur = set(edges)
    for a, b in sorted(cur - known):
        print(
            f"race-report: NEW edge {a} -> {b} (via {edges[(a, b)]}) "
            f"not in {races.LOCK_GRAPH_NAME} — review, then regenerate"
        )
        rc = 1
    for a, b in sorted(known - cur):
        print(
            f"race-report: STALE artifact edge {a} -> {b} no longer in "
            "the tree — regenerate"
        )
        rc = 1
    if rc == 0:
        print(
            f"race-report: lock-order graph OK ({len(cur)} edge(s), "
            "acyclic, artifact in sync)"
        )
    return rc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("dump", nargs="?", default=None,
                    help="sync-runtime JSON dump to judge")
    ap.add_argument("--check-graph", action="store_true",
                    help="check the static lock-order graph against "
                         "the checked-in artifact instead")
    ap.add_argument("--root", default=_ROOT,
                    help="repo root for --check-graph")
    ap.add_argument("--quiet", action="store_true",
                    help="omit the violation stacks")
    args = ap.parse_args(argv)
    if args.check_graph:
        return check_graph(args.root)
    if args.dump is None:
        ap.error("need a dump path (or --check-graph)")
    return judge_dump(args.dump, verbose=not args.quiet)


if __name__ == "__main__":
    sys.exit(main())
