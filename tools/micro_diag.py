"""Micro-compare of diagonal-block factor kernels on the chip: the
native ib-strip chol_unblocked vs the vendor lowering, at panel tile
sizes — the candidate lever for dpotrf's panel-bound ceiling."""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR", os.path.expanduser("~/.cache/jax_comp")
)

import numpy as np


def main():
    import jax

    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp

    from slate_tpu.ops.chol_kernels import chol_unblocked

    print(f"device: {jax.devices()[0]}", flush=True)
    key = jax.random.PRNGKey(0)

    for nb in (256, 512):
        G = jax.random.normal(key, (nb, nb), jnp.float64)
        S = G @ G.T + nb * jnp.eye(nb, dtype=jnp.float64)

        for name, fn in (
            ("chol_unblocked_ib16", lambda d: chol_unblocked(d, 16)),
            ("chol_unblocked_ib32", lambda d: chol_unblocked(d, 32)),
            ("vendor_cholesky", lambda d: jax.lax.linalg.cholesky(d)),
        ):
            sj = jax.jit(lambda d, fn=fn: fn(d).ravel()[-1] + fn(d).ravel()[0])
            try:
                float(np.asarray(sj(S)))
            except Exception as e:
                print(f"nb={nb} {name}: FAILED {type(e).__name__}", flush=True)
                continue
            best = 1e9
            for t in range(3):
                St = S + (t + 1) * 1e-13
                t0 = time.time()
                float(np.asarray(sj(St)))
                best = min(best, time.time() - t0)
            gf = (nb**3 / 3.0) / best / 1e9
            print(f"nb={nb} {name:22s} {best*1e3:8.2f} ms  {gf:7.1f} GF/s",
                  flush=True)


if __name__ == "__main__":
    main()
