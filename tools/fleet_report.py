#!/usr/bin/env python
"""Verdict over a fleet drill's merged metrics JSONL.

Reads the ``tools/metrics_merge.py`` fan-in of a fleet run (router +
per-host dumps, ``--tag``-ed) and judges the cross-process defense
fabric's core claims:

* **zero hung futures** — ``fleet.submitted`` reconciles EXACTLY
  against ``fleet.delivered + fleet.typed_errors``: every admitted
  request resolved, as a value or a typed error, through host death,
  partitions, hedges and drain.
* **zero silent wrong answers** — ``fleet.bad_results == 0`` (the
  drill's client-side reference checks count through
  ``fleet.note_bad_result``; one nonzero means an SDC crossed the
  certificate fence and reached a caller).
* **host death contained** — ``faults.injected.host_death`` implies
  ``fleet.host_dead`` (detected) and ``fleet.redispatched`` (the
  inflight work moved) — the SITE_SPECS recovery join, spelled out
  here because the fleet gate wants the direction, not just presence.
* **SDC quarantined AND probe-recovered** — ``faults.injected.
  sdc_solve`` implies ``fleet.cert.fail > 0``, ``fleet.quarantined >=
  1`` and ``fleet.unquarantined >= 1`` (the cooldown probe brought the
  host back — quarantine without recovery is capacity loss, not
  defense).
* **global quota holds** — ``fleet.rejected_quota > 0`` (the abuser
  was refused at the ROUTER, fleet-wide) and, with ``--victim``/
  ``--p99-budget``, the victim tenant's
  ``fleet.latency.tenant.<victim>.total`` p99 stays within budget.
* **stitched trace is whole** — the ``fleet.trace_orphans`` gauge
  (recorded by the drill from ``tools/trace_stitch.py``) is present
  (``--require-stitch``) and zero.
* **transient RPC faults absorbed** — ``faults.injected.rpc_timeout``
  implies ``fleet.rpc_retries > 0``; ``faults.injected.host_partition``
  implies any of its recovery family (retries, re-dispatch, host-dead
  detection) fired.

Rows carrying ``"src"`` (the per-host view) are skipped for the global
checks — the untagged rows ARE the preserved global sums.  Stdlib-only
by contract.  Exits nonzero when any check fails, so
``run_tests.py --fleet`` can gate on it.

Usage:
    python tools/metrics_merge.py --tag router --tag host0 --tag host1 \\
        router.jsonl host0.metrics.jsonl host1.metrics.jsonl -o merged.jsonl
    python tools/fleet_report.py merged.jsonl --victim tenant_b \\
        --p99-budget 2.0 --require-stitch
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional, Tuple


def load(path: str) -> Tuple[Dict[str, float], Dict[str, object],
                             Dict[str, dict]]:
    """(counters, gauges, hists) — untagged global rows only."""
    counters: Dict[str, float] = {}
    gauges: Dict[str, object] = {}
    hists: Dict[str, dict] = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            r = json.loads(line)
            if "src" in r:
                continue  # per-host view; globals are the judged rows
            t = r.get("type")
            if t == "counter":
                counters[r["name"]] = (
                    counters.get(r["name"], 0.0) + float(r["value"])
                )
            elif t == "gauge":
                gauges[r["name"]] = r["value"]
            elif t == "hist":
                hists[r["name"]] = r
    return counters, gauges, hists


def checks(counters: Dict[str, float], gauges: Dict[str, object],
           hists: Dict[str, dict], victim: Optional[str] = None,
           p99_budget: Optional[float] = None,
           require_stitch: bool = False) -> List[Tuple[str, bool, str]]:
    """(name, ok, detail) rows — the verdict table."""
    c = lambda n: counters.get(n, 0.0)  # noqa: E731
    out: List[Tuple[str, bool, str]] = []

    sub, dlv, terr = c("fleet.submitted"), c("fleet.delivered"), \
        c("fleet.typed_errors")
    out.append((
        "no hung futures", sub > 0 and sub == dlv + terr,
        f"submitted={sub:.0f} delivered={dlv:.0f} typed_errors={terr:.0f}",
    ))
    out.append((
        "no silent wrong answers", c("fleet.bad_results") == 0,
        f"bad_results={c('fleet.bad_results'):.0f}",
    ))
    if c("faults.injected.host_death") > 0:
        out.append((
            "host death contained",
            c("fleet.host_dead") >= 1 and c("fleet.redispatched") >= 1,
            f"host_dead={c('fleet.host_dead'):.0f} "
            f"redispatched={c('fleet.redispatched'):.0f}",
        ))
    if c("faults.injected.sdc_solve") > 0:
        out.append((
            "sdc quarantined + probe-recovered",
            c("fleet.cert.fail") > 0 and c("fleet.quarantined") >= 1
            and c("fleet.unquarantined") >= 1,
            f"cert_fail={c('fleet.cert.fail'):.0f} "
            f"quarantined={c('fleet.quarantined'):.0f} "
            f"unquarantined={c('fleet.unquarantined'):.0f}",
        ))
    if victim is not None:
        out.append((
            "abuser refused fleet-wide", c("fleet.rejected_quota") > 0,
            f"rejected_quota={c('fleet.rejected_quota'):.0f}",
        ))
        h = hists.get(f"fleet.latency.tenant.{victim}.total")
        p99 = h.get("p99") if h else None
        if p99_budget is not None:
            out.append((
                f"victim '{victim}' p99 holds",
                p99 is not None and float(p99) <= p99_budget,
                f"p99={p99} budget={p99_budget:g}"
                + ("" if h else " (hist missing)"),
            ))
    orphans = gauges.get("fleet.trace_orphans")
    if require_stitch or orphans is not None:
        out.append((
            "stitched trace whole",
            orphans is not None and float(orphans) == 0,
            f"trace_orphans={orphans}"
            + ("" if orphans is not None else " (gauge missing)"),
        ))
    if c("faults.injected.rpc_timeout") > 0:
        out.append((
            "rpc timeouts absorbed", c("fleet.rpc_retries") > 0,
            f"rpc_retries={c('fleet.rpc_retries'):.0f}",
        ))
    if c("faults.injected.host_partition") > 0:
        sig = (c("fleet.rpc_retries") + c("fleet.redispatched")
               + c("fleet.host_dead"))
        out.append((
            "partition contained", sig > 0,
            f"rpc_retries+redispatched+host_dead={sig:.0f}",
        ))
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("jsonl", help="merged metrics JSONL from a fleet run")
    ap.add_argument("--victim", default=None,
                    help="victim tenant name (arms the quota checks)")
    ap.add_argument("--p99-budget", type=float, default=None,
                    help="victim p99 bound in seconds")
    ap.add_argument("--require-stitch", action="store_true",
                    help="fail when the fleet.trace_orphans gauge is "
                         "absent (the drill must have run trace_stitch)")
    args = ap.parse_args(argv)
    counters, gauges, hists = load(args.jsonl)
    if not any(n.startswith("fleet.") for n in counters):
        print("no fleet.* counters in this JSONL (fleet drill off?)")
        return 2
    rows = checks(counters, gauges, hists, victim=args.victim,
                  p99_budget=args.p99_budget,
                  require_stitch=args.require_stitch)
    failed = 0
    for name, ok, detail in rows:
        tag = "PASS" if ok else "FAIL"
        failed += not ok
        print(f"{tag}  {name:36} {detail}")
    if failed:
        print(f"\n{failed} fleet check(s) failed")
        return 1
    print(f"\nall {len(rows)} fleet checks passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
