#!/usr/bin/env python
"""Elastic-capacity verdict over a metrics JSONL from a ``--scale``
gate run (or any run with the autoscaler armed).

The capacity plane's claim is narrow and checkable: under a traffic
burst a static fleet misses its tail budget, an elastic fleet holds
it, never exceeds ``max_replicas``, hands the lanes back when the
burst passes, and every scale-up it performed was *driven* — the
decision's own recorded snapshot shows the pressure that forced it.
This tool re-derives all of that from the dump alone:

* **Decision timeline** — every ``{"kind": "scale"}`` timeline row
  (the AutoScaler records one per APPLIED decision, snapshot riding
  along), printed in order so an operator can replay the controller's
  reasoning.
* **p99 before/after** — the gate driver replays the same recorded
  burst trace twice (static replicas=1, then elastic) and publishes
  both tails plus the budget as ``scale.gate.*`` gauges; the verdict
  requires the static leg to MISS (otherwise the run proves nothing)
  and the elastic leg to HOLD.
* **Fleet discipline** — peak replicas <= ``max_replicas``, end-of-run
  replicas == ``min_replicas`` (capacity was given back), and
  ``scale.gate.new_lane_compiles == 0`` — steady-state compiles,
  i.e. total ``jit.compilations`` minus the counted pre-traffic lane
  primes (``serve.device_primes``): every scale-up lane was warmed
  inside ``add_replica`` before it took traffic, and no request
  dispatch ever compiled.
* **Driven decisions** — a scale-up row whose snapshot shows
  sub-threshold pressure (or no reason at all) is a flapping
  controller; each one fails the verdict.
* **Over-provision ratio** (informational) — replica-seconds actually
  held / replica-seconds a min-sized fleet would have held over the
  same window: how much capacity elasticity cost beyond the floor.

Exit status: 0 all checks pass, 1 any check failed, 2 unusable input
(no scale evidence in the JSONL at all).

Usage:
    python tools/capacity_report.py /tmp/scale.jsonl
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional


def load(path: str) -> dict:
    """Counters/gauges (cumulative: last value wins, same as every
    sibling report) plus ``kind=scale`` timeline rows in file order."""
    counters: Dict[str, float] = {}
    gauges: Dict[str, object] = {}
    decisions: List[dict] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            r = json.loads(line)
            t = r.get("type")
            if t == "counter":
                counters[r["name"]] = float(r.get("value", 0))
            elif t == "gauge":
                gauges[r["name"]] = r.get("value")
            elif t == "timeline" and r.get("kind") == "scale":
                decisions.append(r)
    decisions.sort(key=lambda r: float(r.get("t_mono", 0.0)))
    return {"counters": counters, "gauges": gauges, "decisions": decisions}


def replica_seconds(decisions: List[dict],
                    t_end: Optional[float] = None) -> Optional[dict]:
    """Integrate the fleet size over the decision timeline.  Each row
    records ``replicas`` (the size the snapshot SAW, i.e. before the
    action) and ``delta`` applied; the level between two decisions is
    the post-action size of the earlier one.  Returns None with fewer
    than two timeline points (no window to integrate)."""
    if not decisions:
        return None
    pts = []
    for d in decisions:
        t = float(d.get("t_mono", 0.0))
        before = int(d.get("replicas", 1))
        delta = int(d.get("delta", 0))
        after = before + delta if d.get("action") == "up" else (
            before - delta if d.get("action") == "down" else before
        )
        pts.append((t, after))
    if t_end is None:
        t_end = pts[-1][0]
    t0 = pts[0][0]
    if t_end <= t0:
        return None
    area = 0.0
    for (t, level), (t_next, _l2) in zip(pts, pts[1:] + [(t_end, 0)]):
        area += level * max(0.0, min(t_next, t_end) - t)
    return {"replica_s": area, "window_s": t_end - t0}


def analyze(path: str) -> dict:
    """Verdict rows (``{check, ok, detail}``) for one capacity JSONL;
    ``usable`` False means no scale evidence at all (exit 2)."""
    data = load(path)
    c, g, decisions = data["counters"], data["gauges"], data["decisions"]
    have_gate = any(k.startswith("scale.gate.") for k in g)
    if not have_gate and not decisions and "scale.decisions" not in c:
        return {"usable": False, "rows": [], "data": data}
    rows: List[dict] = []

    def fget(name: str) -> Optional[float]:
        v = g.get(name)
        return None if v is None else float(v)

    budget = fget("scale.gate.budget_s")
    static_p99 = fget("scale.gate.static_p99_s")
    elastic_p99 = fget("scale.gate.elastic_p99_s")
    if budget is not None:
        if static_p99 is not None:
            rows.append({
                "check": "static leg misses budget",
                "ok": static_p99 > budget,
                "detail": (
                    f"static p99={static_p99 * 1e3:.1f}ms vs budget "
                    f"{budget * 1e3:.1f}ms"
                    + ("" if static_p99 > budget else
                       " — the control leg held; the burst proves nothing")
                ),
            })
        if elastic_p99 is not None:
            rows.append({
                "check": "elastic leg holds budget",
                "ok": elastic_p99 <= budget,
                "detail": (
                    f"elastic p99={elastic_p99 * 1e3:.1f}ms vs budget "
                    f"{budget * 1e3:.1f}ms"
                ),
            })

    ups = int(c.get("scale.up", 0))
    downs = int(c.get("scale.down", 0))
    rows.append({
        "check": "elasticity engaged", "ok": ups >= 1,
        "detail": f"applied: scale.up={ups} scale.down={downs}",
    })

    peak = fget("scale.gate.replica_peak")
    pmax = fget("scale.gate.max_replicas")
    if peak is not None and pmax is not None:
        rows.append({
            "check": "peak <= max_replicas", "ok": peak <= pmax,
            "detail": f"peak={int(peak)} max_replicas={int(pmax)}",
        })
    end = fget("scale.gate.replicas_end")
    pmin = fget("scale.gate.min_replicas")
    if end is not None and pmin is not None:
        rows.append({
            "check": "fleet returned to min", "ok": end == pmin,
            "detail": (
                f"replicas_end={int(end)} min_replicas={int(pmin)}"
                + ("" if end == pmin else " — capacity never given back")
            ),
        })
    nlc = fget("scale.gate.new_lane_compiles")
    if nlc is not None:
        rows.append({
            "check": "scale-up lanes compile-free", "ok": nlc == 0,
            "detail": (
                f"steady_state_compiles={int(nlc)}"
                + (f" (pre-traffic primes={int(primes)})"
                   if (primes := fget("scale.gate.device_primes"))
                   is not None else "")
                + ("" if nlc == 0 else
                   " — a request dispatch compiled against live "
                   "traffic instead of riding a pre-traffic lane "
                   "prime")
            ),
        })

    # every applied scale-up must carry its driving evidence
    up_thresh = fget("scale.gate.up_threshold")
    undriven = []
    for d in decisions:
        if d.get("action") != "up":
            continue
        p = float(d.get("pressure", 0.0))
        reason = str(d.get("reason") or "")
        floor = up_thresh if up_thresh is not None else 0.0
        if not reason or p <= floor:
            undriven.append(d)
    rows.append({
        "check": "scale-ups driven by signal", "ok": not undriven,
        "detail": (
            f"{sum(1 for d in decisions if d.get('action') == 'up')} "
            "up decision(s), every snapshot above threshold"
            if not undriven else ", ".join(
                f"t={float(d.get('t_mono', 0)):.2f}s pressure="
                f"{float(d.get('pressure', 0)):.3f} "
                f"reason={str(d.get('reason') or '')!r}"
                for d in undriven
            )
        ),
    })

    over = None
    rs = replica_seconds(decisions)
    if rs is not None and pmin:
        over = rs["replica_s"] / (pmin * rs["window_s"])
    return {
        "usable": True, "rows": rows, "data": data,
        "decisions": decisions, "overprovision": over,
        "gate": {k: v for k, v in g.items()
                 if k.startswith("scale.gate.")},
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("jsonl", help="metrics JSONL from a --scale gate run")
    args = ap.parse_args(argv)

    res = analyze(args.jsonl)
    if not res["usable"]:
        print(f"{args.jsonl}: no scale.* evidence — not an elastic-"
              "capacity run's JSONL (scaler never armed, or metrics off)",
              file=sys.stderr)
        return 2

    print(f"capacity verdict: {args.jsonl}")
    if res["gate"]:
        print("  gate gauges: " + "  ".join(
            f"{k.split('scale.gate.')[1]}={v}"
            for k, v in sorted(res["gate"].items())
        ))
    if res["overprovision"] is not None:
        print(f"  over-provision ratio: {res['overprovision']:.2f}x "
              "(replica-seconds held / min-fleet replica-seconds)")
    if res["decisions"]:
        print("  decision timeline:")
        for d in res["decisions"]:
            print(
                f"    t={float(d.get('t_mono', 0)):10.3f}s "
                f"{d.get('action', '?'):4} delta={d.get('delta', 0)} "
                f"replicas={d.get('replicas', '?')} "
                f"pressure={float(d.get('pressure', 0)):.3f} "
                f"qd={d.get('queue_depth', '?')} "
                f"burn={d.get('burn_ewma', '?')} "
                f"({d.get('reason', '')})"
            )
    print()
    failed = 0
    for row in res["rows"]:
        mark = "ok  " if row["ok"] else "FAIL"
        if not row["ok"]:
            failed += 1
        print(f"  [{mark}] {row['check']}: {row['detail']}")
    print()
    if failed:
        print(f"{failed} check(s) failed")
        return 1
    print("all checks passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
