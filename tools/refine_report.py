#!/usr/bin/env python
"""Per-routine mixed-precision refinement report over a metrics JSONL.

Reads a ``SLATE_TPU_METRICS`` dump from a run that exercised the
``*_mixed`` drivers and prints one row per routine from the
``refine.<routine>.*`` counter family:

    routine            calls  mean_iters  converged  fallbacks  fb_rate

``mean_iters`` counts refinement steps per call in method-independent
units (one IR correction or one GMRES cycle), ``converged`` the calls whose
componentwise backward error passed the tolerance on the refine path,
``fallbacks`` the calls demoted to the full-precision direct solve
(``Option.UseFallbackSolver``).  The ``refine.<routine>.residual``
gauge (last backward error) is shown when present.

Exit status gates CI: nonzero when any routine's fallback rate exceeds
``--max-fallback-rate`` (default 0.5) — a deployment whose mixed path
falls back more often than it converges is paying the low-precision
factor *plus* the full-precision solve on most requests, i.e. strictly
worse than the direct driver, and should switch precision pairs,
method (GMRES-IR survives ~1/eps_factor more conditioning), or back to
the full path.

Usage:
    SLATE_TPU_METRICS=/tmp/refine.jsonl python my_workload.py
    python tools/refine_report.py /tmp/refine.jsonl [--max-fallback-rate 0.5]
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List


def _load(path: str):
    counters: Dict[str, float] = {}
    gauges: Dict[str, float] = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            row = json.loads(line)
            if row.get("type") == "counter":
                counters[row["name"]] = float(row.get("value", 0))
            elif row.get("type") == "gauge":
                gauges[row["name"]] = float(row.get("value", 0))
    return counters, gauges


def analyze(path: str) -> List[dict]:
    """One row per routine seen in the refine.<routine>.* counters."""
    counters, gauges = _load(path)
    routines = sorted(
        name[len("refine."):-len(".calls")]
        for name in counters
        if name.startswith("refine.")
        and name.endswith(".calls")
        and name != "refine.calls"
    )
    rows = []
    for r in routines:
        calls = counters.get(f"refine.{r}.calls", 0)
        fallbacks = counters.get(f"refine.{r}.fallbacks", 0)
        rows.append({
            "routine": r,
            "calls": int(calls),
            "iterations": int(counters.get(f"refine.{r}.iterations", 0)),
            "converged": int(counters.get(f"refine.{r}.converged", 0)),
            "fallbacks": int(fallbacks),
            "fallback_rate": (fallbacks / calls) if calls else 0.0,
            "residual": gauges.get(f"refine.{r}.residual"),
        })
    return rows


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("jsonl", help="metrics JSONL from a *_mixed run")
    ap.add_argument(
        "--max-fallback-rate", type=float, default=0.5,
        help="fail (exit 1) when any routine's fallbacks/calls exceeds "
             "this (default 0.5)",
    )
    args = ap.parse_args(argv)

    rows = analyze(args.jsonl)
    if not rows:
        print("no refine.<routine>.* counters in this JSONL "
              "(no *_mixed drivers ran, or metrics were off)")
        return 0
    hdr = (f"{'routine':18} {'calls':>6} {'mean_iters':>11} "
           f"{'converged':>10} {'fallbacks':>10} {'fb_rate':>8} "
           f"{'last_berr':>10}")
    print(hdr)
    print("-" * len(hdr))
    over = []
    for r in rows:
        mean_it = r["iterations"] / r["calls"] if r["calls"] else 0.0
        berr = f"{r['residual']:10.2e}" if r["residual"] is not None else f"{'-':>10}"
        print(
            f"{r['routine']:18} {r['calls']:6d} {mean_it:11.1f} "
            f"{r['converged']:10d} {r['fallbacks']:10d} "
            f"{r['fallback_rate']:8.2f} {berr}"
        )
        if r["fallback_rate"] > args.max_fallback_rate:
            over.append(r["routine"])
    if over:
        print(
            f"\nfallback rate over {args.max_fallback_rate:.2f} for: "
            f"{', '.join(over)} — the mixed path is paying factor+direct "
            "on most requests; change the pair/method or serve at full "
            "precision"
        )
        return 1
    print("\nall routines within the fallback-rate budget")
    return 0


if __name__ == "__main__":
    sys.exit(main())
