"""In-situ ib sweep for geqrf_fast / lu panels at n=8192 (round-5 panel
decision; see profile_qr_panel.py for the standalone panel numbers that
refuted the TSQR and CholQR panel alternatives on this chip)."""
import os, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.expanduser("~/.cache/jax_comp"))
import numpy as np

def main():
    import jax
    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp
    from slate_tpu.ops.qr_fast import geqrf_fast
    print(f"device: {jax.devices()[0]}", flush=True)
    rng = np.random.default_rng(0)
    n = 8192
    M = jnp.asarray(rng.standard_normal((n, n)))
    for ib in (32, 64, 128):
        fn = jax.jit(lambda x, ib=ib: geqrf_fast(x, 512, ib)[0])
        def run(x):
            return float(np.asarray(fn(x).ravel()[-1]))
        for attempt in range(4):
            try:
                run(M); break
            except Exception as e:
                print(f" [retry {type(e).__name__}]", flush=True); time.sleep(15)
        best = 1e9
        for t in range(2):
            t0 = time.time(); run(M + (t+1)*1e-13)
            best = min(best, time.time() - t0)
        gf = 4.0*n**3/3.0/best/1e9
        print(f"dgeqrf n=8192 ib={ib}: {best:.3f}s {gf:.1f} GF/s", flush=True)

if __name__ == "__main__":
    main()
