#!/usr/bin/env python
"""Print a warmup-manifest / serving bucket table from a metrics JSONL.

    python tools/warmup_report.py out.jsonl [--manifest warmup.json]

Rows come from the
``serve.<routine>.<MxNxR>.<dtype>[.tag][.schedule][.precision][.meshPxQ][.phase].b<batch>``
compile/run timers that the serving cache's instrumented executables
record (slate_tpu/serve/cache.py) — the ``schedule`` (PR3),
``precision`` (PR5), ``mesh`` placement (PR8) and ``phase`` (PR10
factor cache: ``solve`` = trsm-only) BucketKey fields are
part of the bucket label (omitted at their defaults
"auto"/"full"/single-device/"full") and get their own columns here;
the mesh column prints ``-`` for single-device buckets and ``PxQ``
for executables traced through the spmd drivers on that submesh.
With ``--manifest`` the table is joined against the warmup manifest
so buckets that were never compiled in this JSONL (stale manifest
entries) and compiles missing from the manifest (warmup gap — the
next cold start pays them) are both flagged; manifest entries that
predate the schedule/precision/mesh/phase fields are flagged
``legacy(...)``
— they load with the documented defaults (mesh-less entries load as
single-device) and re-serialize canonically on the next manifest
flush.  Entries with no cost record anywhere (a devmon-off build or a
pre-PR11 writer) are flagged ``no-cost`` separately — the field is
current, the evidence just has not been captured yet.

The arg/temp/peak byte columns and achieved GF/s come from the
device-telemetry registry (PR11, ``SLATE_TPU_DEVMON=1``): per-bucket
``{"type": "cost"}`` JSONL rows captured at build time
(``cost_analysis`` + ``memory_analysis``), falling back to the
manifest entries' persisted ``"cost"`` field; achieved GF/s divides
registry flops by the mean steady-state run wall.

Produce the JSONL with ``SLATE_TPU_METRICS=out.jsonl`` around any
serving workload (examples/ex16_serving.py shows the whole loop).
"""

import argparse
import json
import re
import sys

_BUCKET_RE = re.compile(
    r"^serve\.(?P<bucket>.+)\.b(?P<batch>\d+)\.(?P<kind>compile|run)$"
)

#: non-default label suffixes (buckets.BucketKey.label appends schedule
#: when != "auto", precision when != "full", meshPxQ when sharded, and
#: phase when != "full", in that order)
_SCHEDULES = ("flat", "recursive")
_PRECISIONS = ("mixed",)
_PHASES = ("solve",)
_MESH_RE = re.compile(r"^mesh(\d+x\d+)$")


def load_jsonl(path):
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


def split_label(bucket):
    """(schedule, precision, mesh, phase) parsed off a bucket label's
    tail — the JSONL-only fallback when no manifest is given (a tag
    that collides with a schedule/precision/mesh/phase literal is
    misread here; the manifest join is the ground truth)."""
    parts = bucket.split(".")
    schedule, precision, mesh, phase = "auto", "full", "", "full"
    if parts and parts[-1] in _PHASES:
        phase = parts.pop()
    if parts:
        m = _MESH_RE.match(parts[-1])
        if m:
            mesh = m.group(1)
            parts.pop()
    if parts and parts[-1] in _PRECISIONS:
        precision = parts.pop()
    if parts and parts[-1] in _SCHEDULES:
        schedule = parts.pop()
    return schedule, precision, mesh, phase


_COST_RE = re.compile(r"^serve\.(?P<bucket>.+)\.b(?P<batch>\d+)$")


def bucket_rows(records):
    """{(bucket, batch): {compiles, compile_s, runs, run_s}} from timer rows."""
    rows = {}
    for r in records:
        if r.get("type") != "timer":
            continue
        m = _BUCKET_RE.match(r.get("name", ""))
        if not m:
            continue
        key = (m.group("bucket"), int(m.group("batch")))
        row = rows.setdefault(
            key, {"compiles": 0, "compile_s": 0.0, "runs": 0, "run_s": 0.0}
        )
        if m.group("kind") == "compile":
            row["compiles"] += int(r.get("count", 0))
            row["compile_s"] += float(r.get("total_s", 0.0))
        else:
            row["runs"] += int(r.get("count", 0))
            row["run_s"] += float(r.get("total_s", 0.0))
    return rows


def cost_rows(records):
    """{(bucket, batch): cost-record} from the registry's JSONL rows."""
    rows = {}
    for r in records:
        if r.get("type") != "cost":
            continue
        m = _COST_RE.match(r.get("name", ""))
        if m:
            rows[(m.group("bucket"), int(m.group("batch")))] = r
    return rows


def manifest_index(path):
    """{(bucket_label, batch): {"schedule", "precision", "mesh",
    "legacy"}} — ``legacy`` lists the BucketKey fields this entry's
    manifest JSON omitted (pre-PR3 ``schedule`` / pre-PR5 ``precision``
    / pre-PR8 ``mesh`` writers), so defaulted entries — mesh-less ones
    load as single-device — are visibly flagged rather than silently
    joined."""
    with open(path) as f:
        doc = json.load(f)
    idx = {}
    for e in doc.get("entries", []):
        legacy = [k for k in ("schedule", "precision", "mesh", "phase")
                  if k not in e]
        schedule = str(e.get("schedule", "auto"))
        precision = str(e.get("precision", "full"))
        mesh = str(e.get("mesh", ""))
        phase = str(e.get("phase", "full"))
        bucket = f"{e['routine']}.{e['m']}x{e['n']}x{e['nrhs']}.{e['dtype']}"
        if e.get("tag"):
            bucket += f".{e['tag']}"
        # mirror BucketKey.label: defaults are omitted from the label
        if schedule != "auto":
            bucket += f".{schedule}"
        if precision != "full":
            bucket += f".{precision}"
        if mesh:
            bucket += f".mesh{mesh}"
        if phase != "full":
            bucket += f".{phase}"
        idx[(bucket, int(e.get("batch", 1)))] = {
            "schedule": schedule, "precision": precision, "mesh": mesh,
            "phase": phase, "legacy": legacy,
            "cost": e.get("cost") if isinstance(e.get("cost"), dict)
            else None,
        }
    return idx


def main(argv=None):
    ap = argparse.ArgumentParser(prog="warmup_report")
    ap.add_argument("jsonl", help="metrics JSONL (SLATE_TPU_METRICS output)")
    ap.add_argument("--manifest", default=None,
                    help="warmup manifest JSON to join against")
    args = ap.parse_args(argv)

    records = load_jsonl(args.jsonl)
    rows = bucket_rows(records)
    costs = cost_rows(records)
    midx = manifest_index(args.manifest) if args.manifest else None

    all_keys = sorted(set(rows) | set(costs) | (set(midx) if midx else set()))
    if not all_keys:
        print("(no serve.* bucket timers in this JSONL)")
        return 0

    def _mb(cost, field):
        v = (cost or {}).get(field)
        return f"{v / 1e6:.2f}" if v else "-"

    hdr = (f"{'bucket':44} {'batch':>5} {'schedule':>9} {'precision':>9} "
           f"{'mesh':>6} {'phase':>6} {'compiles':>8} {'compile(s)':>11} "
           f"{'runs':>6} {'mean_run(ms)':>13} {'arg(MB)':>8} "
           f"{'temp(MB)':>9} {'peak(MB)':>9} {'GF/s':>7} {'note':>16}")
    print(hdr)
    print("-" * len(hdr))
    legacy_total = 0
    nocost_total = 0
    for key in all_keys:
        bucket, batch = key
        row = rows.get(key)
        mentry = midx.get(key) if midx is not None else None
        if mentry is not None:
            schedule, precision = mentry["schedule"], mentry["precision"]
            mesh, phase = mentry["mesh"], mentry["phase"]
        else:
            schedule, precision, mesh, phase = split_label(bucket)
        # registry record: the JSONL cost row when this run captured
        # one, else the manifest entry's persisted "cost" field
        cost = costs.get(key) or (mentry or {}).get("cost")
        mesh_col = mesh or "-"  # "-" = single-device placement
        notes = []
        if midx is not None:
            if mentry is None:
                notes.append("unlisted")  # compiled here, not in manifest
            elif row is None or row["compiles"] == 0:
                notes.append("stale?")  # in manifest, never compiled here
            if mentry is not None and mentry["legacy"]:
                legacy_total += 1
                notes.append(
                    "legacy(%s)" % (
                        "all" if len(mentry["legacy"]) == 4
                        else "+".join(mentry["legacy"])
                    )
                )
            if mentry is not None and cost is None:
                # distinct from legacy: a current-format manifest
                # written with devmon off simply carries no evidence
                # yet — "predates the field" would be a false claim
                nocost_total += 1
                notes.append("no-cost")
        note = ",".join(notes)
        cost_cols = (f"{_mb(cost, 'argument_bytes'):>8} "
                     f"{_mb(cost, 'temp_bytes'):>9} "
                     f"{_mb(cost, 'peak_bytes'):>9}")
        if row is None:
            print(f"{bucket:44} {batch:5d} {schedule:>9} {precision:>9} "
                  f"{mesh_col:>6} {phase:>6} {0:8d} {'-':>11} {0:6d} "
                  f"{'-':>13} {cost_cols} {'-':>7} {note:>16}")
            continue
        mean_run = (row["run_s"] / row["runs"] * 1e3) if row["runs"] else 0.0
        gfs = "-"
        flops = (cost or {}).get("flops") or (cost or {}).get("flops_model")
        if flops and row["runs"] and row["run_s"] > 0:
            gfs = f"{flops * row['runs'] / row['run_s'] / 1e9:.2f}"
        print(
            f"{bucket:44} {batch:5d} {schedule:>9} {precision:>9} "
            f"{mesh_col:>6} {phase:>6} {row['compiles']:8d} "
            f"{row['compile_s']:11.2f} "
            f"{row['runs']:6d} {mean_run:13.2f} {cost_cols} {gfs:>7} "
            f"{note:>16}"
        )
    total_c = sum(r["compile_s"] for r in rows.values())
    print(f"\ntotal compile wall: {total_c:.2f}s over "
          f"{sum(r['compiles'] for r in rows.values())} compiles; "
          f"warmed steady-state pays none of it")
    if legacy_total:
        print(f"{legacy_total} manifest entr"
              f"{'y' if legacy_total == 1 else 'ies'} predate the "
              "schedule/precision/mesh/phase fields (defaulted to "
              "auto/full/single-device/full); re-save the manifest to "
              "upgrade in place")
    if nocost_total:
        print(f"{nocost_total} manifest entr"
              f"{'y' if nocost_total == 1 else 'ies'} carr"
              f"{'ies' if nocost_total == 1 else 'y'} no cost record "
              "(built with devmon off, or a pre-PR11 writer); rebuild "
              "once with SLATE_TPU_DEVMON=1 to bake the evidence in")
    return 0


if __name__ == "__main__":
    sys.exit(main())
