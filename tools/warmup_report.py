#!/usr/bin/env python
"""Print a warmup-manifest / serving bucket table from a metrics JSONL.

    python tools/warmup_report.py out.jsonl [--manifest warmup.json]

Rows come from the ``serve.<routine>.<MxNxR>.<dtype>[.tag].b<batch>``
compile/run timers that the serving cache's instrumented executables
record (slate_tpu/serve/cache.py); with ``--manifest`` the table is
joined against the warmup manifest so buckets that were never compiled
in this JSONL (stale manifest entries) and compiles missing from the
manifest (warmup gap — the next cold start pays them) are both flagged.

Produce the JSONL with ``SLATE_TPU_METRICS=out.jsonl`` around any
serving workload (examples/ex16_serving.py shows the whole loop).
"""

import argparse
import json
import re
import sys

_BUCKET_RE = re.compile(r"^serve\.(?P<bucket>.+)\.b(?P<batch>\d+)\.(?P<kind>compile|run)$")


def load_jsonl(path):
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


def bucket_rows(records):
    """{(bucket, batch): {compiles, compile_s, runs, run_s}} from timer rows."""
    rows = {}
    for r in records:
        if r.get("type") != "timer":
            continue
        m = _BUCKET_RE.match(r.get("name", ""))
        if not m:
            continue
        key = (m.group("bucket"), int(m.group("batch")))
        row = rows.setdefault(
            key, {"compiles": 0, "compile_s": 0.0, "runs": 0, "run_s": 0.0}
        )
        if m.group("kind") == "compile":
            row["compiles"] += int(r.get("count", 0))
            row["compile_s"] += float(r.get("total_s", 0.0))
        else:
            row["runs"] += int(r.get("count", 0))
            row["run_s"] += float(r.get("total_s", 0.0))
    return rows


def manifest_keys(path):
    with open(path) as f:
        doc = json.load(f)
    keys = set()
    for e in doc.get("entries", []):
        bucket = f"{e['routine']}.{e['m']}x{e['n']}x{e['nrhs']}.{e['dtype']}"
        if e.get("tag"):
            bucket += f".{e['tag']}"
        keys.add((bucket, int(e.get("batch", 1))))
    return keys


def main(argv=None):
    ap = argparse.ArgumentParser(prog="warmup_report")
    ap.add_argument("jsonl", help="metrics JSONL (SLATE_TPU_METRICS output)")
    ap.add_argument("--manifest", default=None,
                    help="warmup manifest JSON to join against")
    args = ap.parse_args(argv)

    records = load_jsonl(args.jsonl)
    rows = bucket_rows(records)
    mkeys = manifest_keys(args.manifest) if args.manifest else None

    all_keys = sorted(set(rows) | (mkeys or set()))
    if not all_keys:
        print("(no serve.* bucket timers in this JSONL)")
        return 0

    hdr = (f"{'bucket':44} {'batch':>5} {'compiles':>8} {'compile(s)':>11} "
           f"{'runs':>6} {'mean_run(ms)':>13} {'note':>10}")
    print(hdr)
    print("-" * len(hdr))
    for key in all_keys:
        bucket, batch = key
        row = rows.get(key)
        note = ""
        if mkeys is not None:
            if key not in mkeys:
                note = "unlisted"  # compiled here, missing from manifest
            elif row is None or row["compiles"] == 0:
                note = "stale?"  # in manifest, never compiled in this JSONL
        if row is None:
            print(f"{bucket:44} {batch:5d} {0:8d} {'-':>11} {0:6d} "
                  f"{'-':>13} {note:>10}")
            continue
        mean_run = (row["run_s"] / row["runs"] * 1e3) if row["runs"] else 0.0
        print(
            f"{bucket:44} {batch:5d} {row['compiles']:8d} "
            f"{row['compile_s']:11.2f} {row['runs']:6d} {mean_run:13.2f} "
            f"{note:>10}"
        )
    total_c = sum(r["compile_s"] for r in rows.values())
    print(f"\ntotal compile wall: {total_c:.2f}s over "
          f"{sum(r['compiles'] for r in rows.values())} compiles; "
          f"warmed steady-state pays none of it")
    return 0


if __name__ == "__main__":
    sys.exit(main())
