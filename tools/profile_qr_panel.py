"""On-chip cost model of the geqrf panel (ops/qr_fast._qr_panel_strips).

Round-4 finding: panels are 1.9 s of dgeqrf's 2.59 s at n=8192.  This
tool separates the candidate cost terms so the round-5 panel redesign
targets the real one:

* latency term: per-column fixed dispatch cost  -> time vs m flat
* bandwidth term: per-column strip-tail traffic -> time ~ m * ib

Sweeps m x ib for one (m, 512) panel, plus the small-factorization
floor (vendor vs native chol at 256/512 — the CholQR2 panel
alternative's binding cost).

Run: python tools/profile_qr_panel.py
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR", os.path.expanduser("~/.cache/jax_comp")
)

import numpy as np


def main() -> int:
    import jax

    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp

    from slate_tpu.ops.qr_fast import _qr_panel_strips

    print(f"device: {jax.devices()[0]}", flush=True)
    rng = np.random.default_rng(0)

    def timed(fn, *a, tries=3):
        """Steady-state wall time with HOST READBACK as the barrier:
        block_until_ready is NOT a reliable execution barrier over this
        tunnel (bench.py methodology) — a device-side scalar reduce +
        one-element readback is."""

        def run(args):
            out = fn(*args)
            s = jax.tree.leaves(out)[0].ravel()[-1]
            return float(np.asarray(s))

        last = None
        for attempt in range(4):
            try:
                run(a)
                break
            except Exception as e:
                last = e
                print(f"  [retry {attempt+1}: {type(e).__name__}]", flush=True)
                time.sleep(10.0 * (attempt + 1))
        else:
            raise last
        best = 1e9
        for t in range(tries):
            a2 = tuple(x + (t + 1) * 1e-13 for x in a)
            t0 = time.time()
            run(a2)
            best = min(best, time.time() - t0)
        return best

    w = 512
    for m in (1024, 2048, 8192):
        row = []
        for ib in (16, 32, 64, 128):
            P = jnp.asarray(rng.standard_normal((m, w)))
            fn = jax.jit(lambda P, ib=ib: _qr_panel_strips(P, ib)[0])
            dt = timed(fn, P)
            row.append(f"ib={ib}: {dt*1e3:7.1f}ms")
        print(f"panel m={m:5d} w={w}: " + "  ".join(row), flush=True)

    # vmapped chunk QR (the TSQR level-0 candidate): 8 x (1024, 512)
    P8 = jnp.asarray(rng.standard_normal((8, 1024, w)))
    fn8 = jax.jit(
        lambda P: jax.vmap(lambda x: _qr_panel_strips(x, 32)[0])(P)
    )
    dt = timed(fn8, P8)
    print(f"vmapped 8x(1024,512) chunk QR ib=32: {dt*1e3:7.1f}ms", flush=True)

    # small-factorization floor for CholQR-style panels
    from slate_tpu.ops.chol_kernels import chol_unblocked, cholesky

    for nb in (256, 512):
        G = jnp.asarray(rng.standard_normal((nb, nb)))
        S = G @ G.T + nb * jnp.eye(nb, dtype=jnp.float64)
        ent = [
            ("vendor_chol", jax.jit(lambda d: jax.lax.linalg.cholesky(d))),
            ("unblocked_ib32", jax.jit(lambda d: chol_unblocked(d, 32))),
            ("blocked_recipe", jax.jit(lambda d: cholesky(d, max(nb // 4, 64)))),
        ]
        out = []
        for name, fn in ent:
            try:
                dt = timed(fn, S)
                out.append(f"{name}: {dt*1e3:6.1f}ms")
            except Exception as e:
                out.append(f"{name}: FAIL({type(e).__name__})")
        print(f"chol n={nb}: " + "  ".join(out), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
