"""On-chip phase/level profiler for the native stedc (ops/stedc.py).

Round-4 finding: stedc+unmtr_hb2st went 7.3 s (n=2048) -> 324 s
(n=4096) on the chip — a toolchain interaction, not algorithmic
scaling.  This tool isolates it: it re-runs the bottom-up Cuppen tree
with ONE JIT PER LEVEL (timing each level at steady state), and for
the largest levels times each merge phase (setup/sort, deflation
while_loop, secular roots, Lowner assembly, back-rotation gemm)
separately.

Thin wrapper over the shared measurement layer: the steady-state
host-readback-barrier timing (with the tunnel retry loop) lives in
slate_tpu.aux.metrics.measure_steady; every level/phase lands in the
metrics registry, so SLATE_TPU_METRICS=/path/out.jsonl keeps the full
event stream.

Run: python tools/profile_stedc.py --n 2048 4096
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR", os.path.expanduser("~/.cache/jax_comp")
)

import numpy as np


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, nargs="+", default=[2048, 4096])
    ap.add_argument("--phases-from", type=int, default=1024,
                    help="per-phase timing for levels with n2 >= this")
    args = ap.parse_args()

    import jax

    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp

    from slate_tpu.aux import metrics
    from slate_tpu.ops import stedc as M

    metrics.on()

    print(f"device: {jax.devices()[0]}", flush=True)
    rng = np.random.default_rng(0)
    out = {}

    def timed(name, fn, *a):
        return metrics.measure_steady(fn, *a, name=f"profile_stedc.{name}")

    for n in args.n:
        print(f"\n=== n={n} ===", flush=True)
        d = jnp.asarray(rng.standard_normal(n))
        e = jnp.asarray(rng.standard_normal(n - 1))
        dt = d.dtype
        eps = float(jnp.finfo(dt).eps)
        if jax.default_backend() != "cpu":
            eps *= 32.0

        # replicate stedc()'s normalize + pad + leaves
        scale0 = jnp.maximum(jnp.abs(d).max(), jnp.abs(e).max())
        scale = jnp.where(scale0 > 0, scale0, 1.0)
        d = d / scale
        e = e / scale
        N = 1 << int(np.ceil(np.log2(n)))
        bound = jnp.abs(d).max() + 2 * jnp.abs(e).max() + 1.0
        dpad = jnp.concatenate([d, bound * (2.0 + jnp.arange(N - n, dtype=dt))])
        epad = jnp.concatenate([e, jnp.zeros((N - 1 - e.shape[0],), dt)])
        eabs = jnp.abs(epad)
        left = jnp.concatenate([jnp.zeros((1,), dt), eabs])
        right = jnp.concatenate([eabs, jnp.zeros((1,), dt)])
        w = (dpad - left - right).reshape(N, 1)
        QT = jnp.ones((N, 1, 1), dt)

        levels = {}
        merge_b = jax.jit(jax.vmap(M._merge, in_axes=(0, 0, 0, 0, 0, None)),
                          static_argnums=(5,))
        s = 1
        while s < N:
            nm = N // (2 * s)
            w_pairs = w.reshape(nm, 2, s)
            Q_pairs = QT.reshape(nm, 2, s, s)
            e_r = epad[s - 1 :: 2 * s][:nm]
            tsec, (w, QT) = timed(
                f"level_{2 * s}",
                lambda a, b, c, dd, ee: merge_b(a, b, c, dd, ee, eps),
                w_pairs[:, 0], Q_pairs[:, 0], w_pairs[:, 1], Q_pairs[:, 1],
                e_r,
            )
            n2 = 2 * s
            levels[n2] = round(tsec, 3)
            print(f"level n2={n2:5d} x{nm:4d} merges: {tsec:8.3f}s",
                  flush=True)

            # per-phase timing on this level's inputs
            if n2 >= args.phases_from:
                setup = jax.jit(
                    jax.vmap(M._merge_setup, in_axes=(0, 0, 0, 0, 0, None)),
                    static_argnums=(5,))
                t_set, (D, z, QTm, rho, tol) = timed(
                    f"setup_{n2}",
                    lambda a, b, c, dd, ee: setup(a, b, c, dd, ee, eps),
                    w_pairs[:, 0], Q_pairs[:, 0], w_pairs[:, 1],
                    Q_pairs[:, 1], e_r)
                defl = jax.jit(jax.vmap(M._deflate))
                t_def, (D2, z2, QT2, nd) = timed(
                    f"deflate_{n2}", defl, D, z, QTm, rho, tol)
                secu = jax.jit(jax.vmap(M._solve_secular))
                t_sec, (ks, sg, xx, lam) = timed(
                    f"secular_{n2}", secu, D2, z2, rho, nd, tol)
                asse = jax.jit(jax.vmap(M._assemble_u))
                t_ass, Ur = timed(
                    f"assemble_{n2}", asse, D2, z2, nd, ks, sg, xx)

                @jax.jit
                def rot(Ur, QT2, lam):
                    Qo = jnp.einsum("mij,mjk->mik", Ur, QT2,
                                    precision=jax.lax.Precision.HIGHEST)
                    o2 = jnp.argsort(lam, axis=1)
                    return jnp.take_along_axis(
                        Qo, o2[:, :, None], axis=1)

                t_rot, _ = timed(f"rotate_{n2}", rot, Ur, QT2, lam)
                ndefl_frac = float(nd.mean())
                print(f"  phases: setup {t_set:.3f}s  deflate {t_def:.3f}s"
                      f"  secular {t_sec:.3f}s  assemble {t_ass:.3f}s"
                      f"  rotate+sort {t_rot:.3f}s"
                      f"  (nondefl {ndefl_frac:.2f})", flush=True)
                levels[f"{n2}_phases"] = {
                    "setup": round(t_set, 3), "deflate": round(t_def, 3),
                    "secular": round(t_sec, 3), "assemble": round(t_ass, 3),
                    "rotate_sort": round(t_rot, 3),
                }
            s *= 2

        # end-to-end single-jit stedc for the headline number
        t_e2e, (wfull, Qfull) = timed(
            "end_to_end", jax.jit(M.stedc),
            jnp.asarray(rng.standard_normal(n)),
            jnp.asarray(rng.standard_normal(n - 1)))
        print(f"stedc end-to-end (one jit): {t_e2e:.2f}s", flush=True)
        levels["end_to_end"] = round(t_e2e, 3)
        out[n] = levels

    if os.environ.get("SLATE_TPU_METRICS"):
        metrics.dump()
    print(json.dumps({"profile_stedc": out}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
