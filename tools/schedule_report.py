"""Per-routine factorization-schedule report from a metrics JSONL.

Reads the counters/gauges a run exported with SLATE_TPU_METRICS (or
metrics.dump()) and prints, per factorization routine, the model vs
executed FLOPs recorded by the drivers' schedule accounting
(factor.<routine>.flops_model / .flops_exec), the waste ratio, the
schedule's distinct compile-unit count, and the kernel's jit
compilation count — the observability loop for the recursive-schedule
work (ISSUE 3): a deployment can see exactly how much of its
factorization budget is masked-shape waste and how many shapes it paid
compiles for.

Run: python tools/schedule_report.py metrics.jsonl [more.jsonl ...]
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def collect(paths):
    from slate_tpu.aux.metrics import load_jsonl

    counters, gauges = {}, {}
    for path in paths:
        for rec in load_jsonl(path):
            if rec.get("type") == "counter":
                counters[rec["name"]] = (
                    counters.get(rec["name"], 0.0) + rec["value"]
                )
            elif rec.get("type") == "gauge":
                gauges[rec["name"]] = rec["value"]
    return counters, gauges


def report(counters, gauges):
    routines = sorted(
        name.split(".")[1]
        for name in counters
        if name.startswith("factor.")
        and name.endswith(".flops_model")
        and name.count(".") == 2
    )
    lines = []
    hdr = (f"{'routine':12} {'model GFLOP':>12} {'exec GFLOP':>12} "
           f"{'waste':>7} {'units':>6} {'compiles':>9}")
    lines.append(hdr)
    lines.append("-" * len(hdr))
    for r in routines:
        model = counters.get(f"factor.{r}.flops_model", 0.0)
        ex = counters.get(f"factor.{r}.flops_exec", 0.0)
        waste = f"{ex / model:7.3f}" if model > 0 else f"{'n/a':>7}"
        units = gauges.get(f"factor.{r}.compile_units")
        # every kernel variant of the routine counts: <r>.kernel and
        # e.g. <r>.kernel_recursive both record .compilations
        compiles = sum(
            v for k, v in counters.items()
            if k.startswith(f"{r}.kernel") and k.endswith(".compilations")
        ) or counters.get(f"{r}.compilations", 0)
        lines.append(
            f"{r:12} {model / 1e9:12.3f} {ex / 1e9:12.3f} {waste} "
            f"{int(units) if units is not None else '?':>6} "
            f"{int(compiles):>9}"
        )
    tm = counters.get("factor.flops_model", 0.0)
    tx = counters.get("factor.flops_exec", 0.0)
    if tm > 0:
        lines.append("-" * len(hdr))
        lines.append(
            f"{'TOTAL':12} {tm / 1e9:12.3f} {tx / 1e9:12.3f} "
            f"{tx / tm:7.3f}"
        )
    if not routines:
        lines.append("(no factor.* counters in the given JSONL —"
                     " run with SLATE_TPU_METRICS set and metrics on)")
    return "\n".join(lines)


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if not argv:
        print(__doc__.strip())
        return 2
    missing = [p for p in argv if not os.path.exists(p)]
    if missing:
        print(f"no such file: {missing}", file=sys.stderr)
        return 2
    counters, gauges = collect(argv)
    print(report(counters, gauges))
    return 0


if __name__ == "__main__":
    sys.exit(main())
