"""On-chip correctness validation of the large-n native kernel paths.

The pytest suite runs on the virtual CPU mesh and caps n <= 384, so the
n >= 1024 dispatch gates (ops/chol_kernels.py, ops/lu_fast.py,
ops/qr_fast.py, the stedc-backed heev vectors path) never execute there
on the real device.  This script residual-checks each of them ON THE
CHIP at production sizes and prints one summary line per check
(appended to BENCH_NOTES.md's validation table).

Run: python tools/validate_onchip.py [--quick]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR", os.path.expanduser("~/.cache/jax_comp")
)

import numpy as np


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true", help="smaller sizes")
    ap.add_argument("--heev-only", action="store_true")
    ap.add_argument("--n-eig", type=int, default=0,
                    help="override heev size (default 2048, quick: 1024)")
    args = ap.parse_args()

    import jax

    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp

    dev = jax.devices()[0]
    print(f"device: {dev}", flush=True)
    rng = np.random.default_rng(42)
    eps = float(np.finfo(np.float64).eps)
    results = {}

    def report(name, err, bound, secs):
        ok = bool(err <= bound)
        results[name] = {"err": float(err), "bound": float(bound),
                         "seconds": round(secs, 2), "pass": ok}
        print(f"{name:28s} err={err:9.3e} bound={bound:9.3e} "
              f"{'PASS' if ok else 'FAIL'} ({secs:.1f}s)", flush=True)
        return ok

    ok = True

    if not args.heev_only:
        # -- dpotrf: ops/chol_kernels.cholesky --------------------------
        n = 1024 if args.quick else 2048
        A0 = rng.standard_normal((n, n))
        A0 = A0 @ A0.T + n * np.eye(n)
        from slate_tpu.ops.chol_kernels import cholesky

        t0 = time.time()
        L = np.asarray(jax.block_until_ready(cholesky(jnp.asarray(A0), 512)))
        t1 = time.time()
        L = np.tril(L)
        err = np.abs(L @ L.T - A0).max() / (np.abs(A0).max() * n * eps)
        ok &= report("dpotrf_native(n=%d)" % n, err, 100, t1 - t0)

        # -- dgetrf: ops/lu_fast ----------------------------------------
        from slate_tpu.ops.lu_fast import blocked_getrf_fast

        M0 = rng.standard_normal((n, n))
        t0 = time.time()
        lu2d, perm = jax.block_until_ready(
            blocked_getrf_fast(jnp.asarray(M0), 512)
        )
        t1 = time.time()
        lu2d = np.asarray(lu2d)
        perm = np.asarray(perm)
        Lm = np.tril(lu2d, -1) + np.eye(n)
        Um = np.triu(lu2d)
        err = np.abs(Lm @ Um - M0[perm]).max() / (np.abs(M0).max() * n * eps)
        ok &= report("dgetrf_native(n=%d)" % n, err, 100, t1 - t0)

        # -- dgeqrf: ops/qr_fast ----------------------------------------
        from slate_tpu.ops.qr_fast import geqrf_fast
        from slate_tpu.ops.householder import larft, materialize_v

        t0 = time.time()
        fac, taus = jax.block_until_ready(geqrf_fast(jnp.asarray(M0), 512))
        t1 = time.time()
        # reconstruct Q^T A and compare to R (apply the panels)
        Afac = np.asarray(fac)
        R = np.triu(Afac)
        C = jnp.asarray(M0)
        nbp = 512
        for k0 in range(0, n, nbp):
            V = materialize_v(fac[:, k0:k0 + nbp], offset=k0)
            T = larft(V, taus[k0:k0 + nbp])
            W = V.conj().T @ C
            C = C - V @ (T.conj().T @ W)
        QtA = np.asarray(C)
        err = np.abs(QtA - R).max() / (np.abs(M0).max() * n * eps)
        ok &= report("dgeqrf_native(n=%d)" % n, err, 100, t1 - t0)

    # -- heev with vectors through the driver (he2hb + hb2st + stedc +
    #    back-transforms), the full flagship path ------------------------
    n_eig = args.n_eig or (1024 if args.quick else 2048)
    from slate_tpu.drivers import eig
    from slate_tpu.enums import Uplo
    from slate_tpu.matrix.matrix import HermitianMatrix

    H0 = rng.standard_normal((n_eig, n_eig))
    H0 = (H0 + H0.T) / 2
    A = HermitianMatrix.from_global(
        jnp.asarray(H0), 128, uplo=Uplo.Lower
    )

    # The product stage-split path (drivers/eig.py heev_staged): one
    # whole-heev jit at n >= 2048 exceeds what the tunnel's
    # remote-compile service survives ("response body closed"), so the
    # driver compiles the four stages separately, with the native host
    # chaser for stage 2 when available.
    from slate_tpu import native as native_mod
    from slate_tpu.drivers.eig import heev_staged

    print(f"native hb2st: {native_mod.hb2st_available()}", flush=True)
    print("compiling heev stages...", flush=True)
    tc0 = time.time()
    heev_staged(A, vectors=True)
    print(f"heev stages compile+first run: {time.time() - tc0:.1f}s",
          flush=True)
    # perturb the input: the tunnel caches identical dispatches
    # (BENCH_NOTES.md methodology), so timing a replay measures nothing
    A = A._with(data=A.data + jnp.float64(1e-14))
    H0 = H0 + 1e-14
    t0 = time.time()
    w, Zm, stage_t = heev_staged(A, vectors=True)
    t1 = time.time() - t0
    t0 = 0.0
    w = np.asarray(w)
    Zg = np.asarray(Zm.to_global())
    print(f"stage breakdown: {stage_t}", flush=True)
    results["heev_stages"] = dict(stage_t)
    err = np.abs(H0 @ Zg - Zg * w[None, :]).max() / (
        np.abs(H0).max() * n_eig * eps
    )
    orth = np.abs(Zg.T @ Zg - np.eye(n_eig)).max() / (n_eig * eps)
    ok &= report("dheev_vectors(n=%d)" % n_eig, err, 100, t1 - t0)
    ok &= report("dheev_orth(n=%d)" % n_eig, orth, 100, 0.0)
    werr = np.abs(np.sort(w) - np.linalg.eigvalsh(H0)).max() / (
        np.abs(w).max() * n_eig * eps
    )
    ok &= report("dheev_values(n=%d)" % n_eig, werr, 100, 0.0)

    print(json.dumps({"onchip_validation": results, "all_pass": bool(ok)}))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
