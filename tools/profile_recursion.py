"""On-chip sweep of the factorization recursion shape (verdict r4 #5).

The r4 ceiling analysis: the rank-512 trailing update runs at 481 GF/s
(25% of square-gemm), so fattening the coarse updates is the remaining
schedule lever.  This sweeps (nb, coarse_panels) for the native dpotrf
and dgetrf at n=8192 and prints GF/s per configuration — either the
better recipe or the measured refutation for BENCH_NOTES.

Run: python tools/profile_recursion.py [--n 8192]
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR", os.path.expanduser("~/.cache/jax_comp")
)

import numpy as np


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=8192)
    ap.add_argument("--skip-lu", action="store_true")
    args = ap.parse_args()

    import jax

    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp

    from slate_tpu.ops.chol_kernels import blocked_potrf
    from slate_tpu.ops.lu_fast import blocked_getrf_fast

    n = args.n
    print(f"device: {jax.devices()[0]}  n={n}", flush=True)
    rng = np.random.default_rng(0)
    A0 = rng.standard_normal((n, n))
    S = jnp.asarray(A0 @ A0.T / n + 2 * np.eye(n))
    M = jnp.asarray(A0)

    def timed(fn, x, tries=2):
        """Host-readback barrier (block_until_ready is not a reliable
        execution barrier over this tunnel — bench.py methodology)."""

        def run(arg):
            out = fn(arg)
            return float(np.asarray(jax.tree.leaves(out)[0].ravel()[-1]))

        last = None
        for attempt in range(4):
            try:
                run(x)
                break
            except Exception as e:
                last = e
                print(f"  [retry {attempt+1}: {type(e).__name__}]", flush=True)
                time.sleep(10.0 * (attempt + 1))
        else:
            raise last
        best = 1e9
        for t in range(tries):
            t0 = time.time()
            run(x + (t + 1) * 1e-13)
            best = min(best, time.time() - t0)
        return best

    print("--- dpotrf sweep ---", flush=True)
    for nb, cp in [(512, 4), (512, 2), (1024, 4), (1024, 2), (2048, 4),
                   (512, 8), (256, 4)]:
        fn = jax.jit(lambda x, nb=nb, cp=cp: blocked_potrf(x, nb, cp))
        try:
            dt = timed(fn, S)
            gf = (n**3 / 3.0) / dt / 1e9
            print(f"dpotrf nb={nb:5d} coarse={cp}: {dt:6.3f}s {gf:7.1f} GF/s",
                  flush=True)
        except Exception as e:
            print(f"dpotrf nb={nb} coarse={cp}: FAIL {type(e).__name__}",
                  flush=True)

    if not args.skip_lu:
        print("--- dgetrf sweep ---", flush=True)
        for nb, cp in [(512, 4), (512, 2), (1024, 4), (1024, 2)]:
            fn = jax.jit(
                lambda x, nb=nb, cp=cp: blocked_getrf_fast(
                    x, nb, coarse_panels=cp
                )[0]
            )
            try:
                dt = timed(fn, M)
                gf = (2.0 * n**3 / 3.0) / dt / 1e9
                print(f"dgetrf nb={nb:5d} coarse={cp}: {dt:6.3f}s "
                      f"{gf:7.1f} GF/s", flush=True)
            except Exception as e:
                print(f"dgetrf nb={nb} coarse={cp}: FAIL {type(e).__name__}",
                      flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
