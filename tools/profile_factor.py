"""Factorization throughput breakdown on the chip (round-4 ceiling
analysis): measures the f64 gemm denominator at n=8192, the three
factorization totals, their PANEL-ONLY costs, and exact-shape
trailing-gemm proxies, so BENCH_NOTES.md can attribute the gap between
the factorization rates and the chip's own gemm rate.

Thin wrapper over the shared measurement layer: best-of timing with the
host-readback barrier lives in slate_tpu.aux.metrics.measure_best (the
bench.py methodology); every section lands in the metrics registry, so
SLATE_TPU_METRICS=/path/out.jsonl keeps the full event stream.

Run: python tools/profile_factor.py [--n 8192]
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR", os.path.expanduser("~/.cache/jax_comp")
)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=8192)
    ap.add_argument("--trials", type=int, default=3)
    args = ap.parse_args()
    n = args.n

    import jax

    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp

    from slate_tpu.aux import metrics

    metrics.on()

    print(f"device: {jax.devices()[0]}, n={n}", flush=True)
    key = jax.random.PRNGKey(0)
    res = {}

    def put(name, seconds, flops):
        gf = flops / seconds / 1e9
        res[name] = {"seconds": round(seconds, 4), "gflops": round(gf, 1)}
        metrics.gauge(f"profile_factor.{name}.gflops", gf)
        print(f"{name:32s} {seconds:8.3f}s  {gf:9.1f} GF/s", flush=True)

    nb = 512 if n % 512 == 0 and n > 512 else max(n // 4, 1)
    pert = lambda ar, t: (ar[0] + t * 1e-13,) + tuple(ar[1:])  # noqa: E731

    def best(name, fn, fn_args):
        return metrics.measure_best(
            fn, fn_args, trials=args.trials, perturb=pert,
            name=f"profile_factor.{name}",
        )

    # -- denominator: f64 gemm at the same n ---------------------------
    A = jax.random.normal(key, (n, n), jnp.float64)
    B = jax.random.normal(jax.random.PRNGKey(1), (n, n), jnp.float64)
    put("dgemm", best("dgemm", lambda a, b: a @ b, (A, B)), 2.0 * n**3)

    # -- totals --------------------------------------------------------
    from slate_tpu.ops.chol_kernels import blocked_potrf, chol_unblocked
    from slate_tpu.ops.lu_fast import blocked_getrf_fast, _lu_panel_strips
    from slate_tpu.ops.qr_fast import geqrf_fast, _qr_panel_strips

    S = A @ A.T + n * jnp.eye(n, dtype=jnp.float64)
    put("dpotrf_total",
        best("dpotrf", lambda g: blocked_potrf(g, nb), (S,)), n**3 / 3.0)
    put("dgetrf_total",
        best("dgetrf", lambda g: blocked_getrf_fast(g, nb), (A,)),
        2.0 * n**3 / 3.0)
    put("dgeqrf_total",
        best("dgeqrf", lambda g: geqrf_fast(g, nb), (A,)), 4.0 * n**3 / 3.0)

    # -- panel-only costs (the sequential micro-loops) ------------------
    P = jax.random.normal(jax.random.PRNGKey(2), (n, nb), jnp.float64)
    nt = n // nb
    s = best("qr_panel", lambda p: _qr_panel_strips(p, 32), (P,))
    put("qr_panel(mxnb) x nt", s * nt, nt * (2.0 * n * nb * nb))
    s = best("lu_panel", lambda p: _lu_panel_strips(p, p.shape[0], 32), (P,))
    put("lu_panel(mxnb) x nt", s * nt, nt * (n * nb * nb))

    D = S[:nb, :nb]
    s = best("chol_diag", lambda d: chol_unblocked(d, 16), (D,))
    put("chol_diag(nbxnb) x nt", s * nt, nt * (nb**3 / 3.0))

    # -- trailing-gemm proxy: the exact update shapes, chained ----------
    # right-looking trailing updates ~ sum_k (n - k nb) x nb @ nb x (n - k nb)
    def trailing_chain(a):
        out = jnp.zeros((), jnp.float64)
        acc = a
        for k in range(nt - 1):
            h = n - (k + 1) * nb
            L = acc[:h, :nb]
            acc = acc.at[:h, :h].add(-L @ jnp.swapaxes(L, 0, 1) * 1e-20)
            out = out + acc[0, 0]
        return out

    s = best("trailing_syrk_chain", trailing_chain, (A,))
    fl = sum(2.0 * (n - (k + 1) * nb) ** 2 * nb for k in range(nt - 1))
    put("trailing_syrk_chain", s, fl)

    if os.environ.get("SLATE_TPU_METRICS"):
        metrics.dump()
    print(json.dumps(res))


if __name__ == "__main__":
    main()
