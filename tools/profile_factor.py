"""Factorization throughput breakdown on the chip (round-4 ceiling
analysis): measures the f64 gemm denominator at n=8192, the three
factorization totals, their PANEL-ONLY costs, and exact-shape
trailing-gemm proxies, so BENCH_NOTES.md can attribute the gap between
the factorization rates and the chip's own gemm rate.

Run: python tools/profile_factor.py [--n 8192]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR", os.path.expanduser("~/.cache/jax_comp")
)

import numpy as np


def bench(fn, args, trials=3, perturb=None):
    """Best-of wall-clock with input perturbation to defeat the tunnel's
    result cache; the barrier is a SCALAR host readback
    (block_until_ready does not synchronize over this tunnel —
    BENCH_NOTES methodology)."""
    import jax
    import jax.numpy as _jnp

    def _scal(leaf):
        x = _jnp.asarray(leaf).ravel()
        return x[0].astype(_jnp.float64) + x[-1].astype(_jnp.float64)

    def scalarized(*a):
        return sum(_scal(l) for l in jax.tree_util.tree_leaves(fn(*a)))

    sj = jax.jit(scalarized)
    # warmup/compile with a distinct perturbation
    float(np.asarray(sj(*(perturb(args, 17) if perturb else args))))
    best = float("inf")
    for t in range(trials):
        a = args if perturb is None else perturb(args, t)
        jax.block_until_ready(a)
        t0 = time.time()
        float(np.asarray(sj(*a)))
        best = min(best, time.time() - t0)
    return best


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=8192)
    args = ap.parse_args()
    n = args.n

    import jax

    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp

    print(f"device: {jax.devices()[0]}, n={n}", flush=True)
    key = jax.random.PRNGKey(0)
    res = {}

    def put(name, seconds, flops):
        gf = flops / seconds / 1e9
        res[name] = {"seconds": round(seconds, 4), "gflops": round(gf, 1)}
        print(f"{name:32s} {seconds:8.3f}s  {gf:9.1f} GF/s", flush=True)

    nb = 512

    # -- denominator: f64 gemm at the same n ---------------------------
    A = jax.random.normal(key, (n, n), jnp.float64)
    B = jax.random.normal(jax.random.PRNGKey(1), (n, n), jnp.float64)
    gemm = jax.jit(lambda a, b: a @ b)
    pert = lambda ar, t: (ar[0] + t * 1e-13,) + tuple(ar[1:])
    s = bench(gemm, (A, B), perturb=pert)
    put("dgemm", s, 2.0 * n**3)

    # -- totals --------------------------------------------------------
    from slate_tpu.ops.chol_kernels import blocked_potrf
    from slate_tpu.ops.lu_fast import blocked_getrf_fast, _lu_panel_strips
    from slate_tpu.ops.qr_fast import geqrf_fast, _qr_panel_strips

    S = A @ A.T + n * jnp.eye(n, dtype=jnp.float64)
    s = bench(jax.jit(lambda g: blocked_potrf(g, nb)), (S,), perturb=pert)
    put("dpotrf_total", s, n**3 / 3.0)

    s = bench(
        jax.jit(lambda g: blocked_getrf_fast(g, nb)), (A,), perturb=pert
    )
    put("dgetrf_total", s, 2.0 * n**3 / 3.0)

    s = bench(jax.jit(lambda g: geqrf_fast(g, nb)), (A,), perturb=pert)
    put("dgeqrf_total", s, 4.0 * n**3 / 3.0)

    # -- panel-only costs (the sequential micro-loops) ------------------
    P = jax.random.normal(jax.random.PRNGKey(2), (n, nb), jnp.float64)
    s = bench(jax.jit(lambda p: _qr_panel_strips(p, 32)), (P,), perturb=pert)
    nt = n // nb
    put("qr_panel(mxnb) x nt", s * nt, nt * (2.0 * n * nb * nb))

    s = bench(
        jax.jit(lambda p: _lu_panel_strips(p, p.shape[0], 32)), (P,), perturb=pert
    )
    put("lu_panel(mxnb) x nt", s * nt, nt * (n * nb * nb))

    from slate_tpu.ops.chol_kernels import chol_unblocked

    D = S[:nb, :nb]
    s = bench(jax.jit(lambda d: chol_unblocked(d, 16)), (D,), perturb=pert)
    put("chol_diag(nbxnb) x nt", s * nt, nt * (nb**3 / 3.0))

    # -- trailing-gemm proxy: the exact update shapes, chained ----------
    # right-looking trailing updates ~ sum_k (n - k nb) x nb @ nb x (n - k nb)
    def trailing_chain(a):
        out = jnp.zeros((), jnp.float64)
        acc = a
        for k in range(nt - 1):
            h = n - (k + 1) * nb
            L = lax_slice(acc, h, nb)
            acc = acc.at[:h, :h].add(-L @ jnp.swapaxes(L, 0, 1) * 1e-20)
            out = out + acc[0, 0]
        return out

    def lax_slice(a, h, w):
        return a[:h, :w]

    s = bench(jax.jit(trailing_chain), (A,), perturb=pert)
    fl = sum(2.0 * (n - (k + 1) * nb) ** 2 * nb for k in range(nt - 1))
    put("trailing_syrk_chain", s, fl)

    print(json.dumps(res))


if __name__ == "__main__":
    main()
