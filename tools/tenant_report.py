#!/usr/bin/env python
"""Per-tenant admission/fairness report from a metrics JSONL.

    python tools/tenant_report.py out.jsonl \\
        [--p99-budget 0.25 --well-behaved gold] [--abusive flood]

Rows come from the admission plane's capped per-tenant families
(``slate_tpu/serve/admission.py``): ``serve.tenant.<id>.{admitted,
shed,rejected}`` counters, the per-tenant burn tiers
(``serve.tenant.<id>.slo_burn.*``), and the
``serve.latency.tenant.<id>.total`` histograms (p50/p99 per tenant —
the fairness verdict's metric).  Underneath: the service-wide shed /
quota-rejection totals, the overload controller's enter/exit counts,
and the per-bucket adaptive-window trajectory
(``serve.adaptive.<bucket>.window_s`` + widen/shrink counts).

Exit status is the **fairness verdict** (what the ``run_tests.py
--adaptive`` gate fails on):

* ``--p99-budget S --well-behaved T`` — tenant T's total p99 must be
  within S seconds (a budget over a tenant with no latency data fails:
  it verifies nothing);
* ``--abusive T`` — tenant T must have been refused at least once
  (``shed + rejected > 0``): an "overload" run where the abuser was
  never shed proves the controller didn't engage.

Without gate flags the report is informational (exit 0 unless the
JSONL has no per-tenant data at all and a gate was requested).

Produce the JSONL with ``SLATE_TPU_METRICS=out.jsonl`` around any
tenancy-enabled serving workload (``SLATE_TPU_TENANTS=...``).
"""

from __future__ import annotations

import argparse
import json
import re
import sys
from typing import Dict

_EVENTS = ("admitted", "shed", "rejected")
_EVT_RE = re.compile(
    r"^serve\.tenant\.(?P<tenant>.+)\.(?P<event>admitted|shed|rejected)$"
)
_BURN_RE = re.compile(
    r"^serve\.tenant\.(?P<tenant>.+)\.slo_burn\.(?P<tier>requests|"
    r"over_50|over_80|exhausted)$"
)
_LAT_RE = re.compile(r"^serve\.latency\.tenant\.(?P<tenant>.+)\.total$")
_WIN_RE = re.compile(r"^serve\.adaptive\.(?P<bucket>.+)\.window_s$")
_CHG_RE = re.compile(r"^serve\.adaptive\.(?P<bucket>.+)\.(widen|shrink)$")


def load_records(path):
    """Last-value-wins snapshot semantics (the sibling reports' rule:
    summing re-dumped cumulative JSONLs inflates)."""
    counters, gauges, hists = {}, {}, {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            r = json.loads(line)
            if r.get("type") == "counter":
                counters[r["name"]] = float(r.get("value", 0))
            elif r.get("type") == "gauge":
                gauges[r["name"]] = float(r.get("value", 0))
            elif r.get("type") == "hist":
                hists[r["name"]] = r
    return counters, gauges, hists


def tenant_rows(counters, hists) -> Dict[str, dict]:
    rows: Dict[str, dict] = {}

    def row(t):
        return rows.setdefault(
            t, {e: 0 for e in _EVENTS} | {"burn": {}, "latency": None}
        )

    for name, v in counters.items():
        m = _EVT_RE.match(name)
        if m:
            row(m.group("tenant"))[m.group("event")] = int(v)
            continue
        m = _BURN_RE.match(name)
        if m:
            row(m.group("tenant"))["burn"][m.group("tier")] = int(v)
    for name, rec in hists.items():
        m = _LAT_RE.match(name)
        if m:
            row(m.group("tenant"))["latency"] = rec
    return rows


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="tenant_report")
    ap.add_argument("jsonl", help="metrics JSONL (SLATE_TPU_METRICS output)")
    ap.add_argument("--p99-budget", type=float, default=None,
                    help="fairness verdict: the well-behaved tenant's "
                         "total p99 must be within this many seconds")
    ap.add_argument("--well-behaved", default=None, metavar="TENANT",
                    help="tenant the p99 budget applies to")
    ap.add_argument("--abusive", default=None, metavar="TENANT",
                    help="tenant that must show shed+rejected > 0")
    args = ap.parse_args(argv)
    if (args.p99_budget is None) != (args.well_behaved is None):
        # half a gate verifies nothing, silently — refuse loudly
        ap.error("--p99-budget and --well-behaved must be given together")

    counters, gauges, hists = load_records(args.jsonl)
    rows = tenant_rows(counters, hists)
    gating = args.abusive is not None or (
        args.p99_budget is not None and args.well_behaved is not None
    )

    if not rows:
        print("(no serve.tenant.* metrics in this JSONL — did the "
              "stream go through a tenancy-enabled SolverService with "
              "metrics on?)")
        return 1 if gating else 0

    hdr = (f"{'tenant':16} {'admitted':>9} {'shed':>6} {'rejected':>9} "
           f"{'p50(ms)':>8} {'p99(ms)':>8} {'burn>80%':>9} {'exhausted':>10}")
    print(hdr)
    print("-" * len(hdr))
    failures = []
    for t in sorted(rows):
        r = rows[t]
        lat = r["latency"]
        p50 = f"{lat['p50'] * 1e3:.1f}" if lat else "-"
        p99 = f"{lat['p99'] * 1e3:.1f}" if lat else "-"
        burn = r["burn"]
        print(f"{t:16} {r['admitted']:9d} {r['shed']:6d} "
              f"{r['rejected']:9d} {p50:>8} {p99:>8} "
              f"{burn.get('over_80', 0):9d} {burn.get('exhausted', 0):10d}")

    shed = int(counters.get("serve.shed", 0))
    quota = int(counters.get("serve.rejected_quota", 0))
    share = int(counters.get("serve.rejected_share", 0))
    overflow = int(counters.get("serve.tenant_overflow", 0))
    print(f"\nservice: shed={shed} rejected_quota={quota} "
          f"rejected_share={share}"
          + (f" tenant_overflow={overflow}" if overflow else ""))
    enters = int(counters.get("serve.overload.enter", 0))
    exits = int(counters.get("serve.overload.exit", 0))
    if enters or exits:
        lvl = gauges.get("serve.overload.level")
        print(f"overload: {enters} escalations, {exits} recoveries"
              + (f", final level {int(lvl)}" if lvl is not None else ""))

    windows = {m.group("bucket"): v for name, v in gauges.items()
               if (m := _WIN_RE.match(name))}
    if windows:
        changes: Dict[str, int] = {}
        for name, v in counters.items():
            m = _CHG_RE.match(name)
            if m:
                changes[m.group("bucket")] = (
                    changes.get(m.group("bucket"), 0) + int(v)
                )
        print("adaptive windows:")
        for b in sorted(windows):
            print(f"  {b:40} {windows[b] * 1e3:8.3f} ms "
                  f"({changes.get(b, 0)} changes)")

    if args.p99_budget is not None and args.well_behaved is not None:
        lat = rows.get(args.well_behaved, {}).get("latency")
        if lat is None:
            failures.append(
                f"well-behaved tenant {args.well_behaved!r} has no "
                "latency data — the budget verified nothing"
            )
        elif lat["p99"] > args.p99_budget:
            failures.append(
                f"well-behaved tenant {args.well_behaved!r} p99 "
                f"{lat['p99'] * 1e3:.1f} ms exceeds the "
                f"{args.p99_budget * 1e3:.1f} ms budget"
            )
    if args.abusive is not None:
        r = rows.get(args.abusive)
        refused = (r["shed"] + r["rejected"]) if r else 0
        if refused <= 0:
            failures.append(
                f"abusive tenant {args.abusive!r} was never refused "
                "(shed + rejected == 0): the controller did not engage"
            )

    if failures:
        for f in failures:
            print(f"FAIL: {f}")
        return 1
    if gating:
        print("\nfairness verdict ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
