#!/usr/bin/env python
"""Factor-cache report over a metrics JSONL: the per-fingerprint
hit/miss/evict/bytes table, plus the global lifecycle counters.

Reads a ``SLATE_TPU_METRICS`` dump from a factor-cache run
(``SLATE_TPU_FACTOR_CACHE=1`` or an explicit
``SolverService(factor_cache=...)``) and groups the
``serve.factor_cache.fp.<fp12>.*`` counters by fingerprint:

    fp            hit  miss  evict  inval  update  stale      bytes
    ------------  ---  ----  -----  -----  ------  -----  ---------
    3f2a9c01d4e7   37     1      0      0       1      0    2097152

A **repeated-A stream that never hits** is the failure this tool
gates on: some fingerprint was requested at least twice (miss >= 2)
with zero eviction or invalidation to explain the re-miss, and the
whole run recorded zero hits — the cache is configured but not
serving (a keying regression, a broken hit path, or an entry that
never survived ``put``).  That exits nonzero so CI can gate on it
(``run_tests.py --factor`` does).  A stream with hits, or whose
re-misses are explained by eviction/invalidation pressure, passes.

Usage:
    SLATE_TPU_METRICS=/tmp/fc.jsonl SLATE_TPU_FACTOR_CACHE=1 python app.py
    python tools/factor_report.py /tmp/fc.jsonl
"""

from __future__ import annotations

import argparse
import json
import sys
from collections import defaultdict
from typing import Dict

PREFIX = "serve.factor_cache.fp."

#: per-fp columns, in display order (counter suffixes under PREFIX)
EVENTS = ("hit", "miss", "evict", "invalidate", "update",
          "update_refactor", "stale", "refactor", "spill",
          "cross_lane_hit", "uncacheable")

#: global counters summarized under the table
GLOBALS = tuple(f"serve.factor_cache.{e}" for e in EVENTS)

#: device-arena lifecycle counters (fabric/arena.py), global +
#: ``serve.arena.lane.<lane>.*``
ARENA_PREFIX = "serve.arena."
ARENA_EVENTS = ("hit", "miss", "upload_bytes", "upload_avoided_bytes",
                "cross_replica", "spill", "evict", "drop")


def _rows(path: str):
    counters: Dict[str, float] = {}
    gauges: Dict[str, float] = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            row = json.loads(line)
            if row.get("type") == "counter":
                counters[row["name"]] = float(row.get("value", 0))
            elif row.get("type") == "gauge":
                gauges[row["name"]] = float(row.get("value", 0))
    return counters, gauges


def analyze(path: str):
    """(per-fp table rows, global counter dict, flagged?)."""
    counters, gauges = _rows(path)
    per_fp: Dict[str, dict] = defaultdict(lambda: {e: 0 for e in EVENTS})
    for name, v in counters.items():
        if not name.startswith(PREFIX):
            continue
        rest = name[len(PREFIX):]
        fp, _, event = rest.partition(".")
        if event in EVENTS:
            per_fp[fp][event] = int(v)
    for name, v in gauges.items():
        if name.startswith(PREFIX) and name.endswith(".bytes"):
            fp = name[len(PREFIX):].rsplit(".", 1)[0]
            per_fp[fp]["bytes"] = int(v)
    table = [
        {"fp": fp, "bytes": row.get("bytes", 0), **row}
        for fp, row in sorted(per_fp.items())
    ]
    tot = {g.rsplit(".", 1)[1]: int(counters.get(g, 0)) for g in GLOBALS}
    # the gate: a repeated-A stream (same fp missed >= 2 times) with no
    # eviction/invalidation pressure to explain it, and zero hits
    # anywhere — the cache is on but not serving
    total_hits = tot.get("hit", 0)
    repeated_unexplained = any(
        r["miss"] >= 2 and r["evict"] == 0 and r["invalidate"] == 0
        for r in table
    )
    flagged = bool(table) and total_hits == 0 and repeated_unexplained
    return table, tot, flagged


def analyze_arena(path: str) -> dict:
    """Device-arena summary of a dump: global + per-lane event
    counters, the residency byte gauge, and the devmon HBM gauge each
    lane last sampled.  ``legacy`` is True for a pre-arena dump —
    factor-cache counters present but not one ``serve.arena.*`` name
    (an old JSONL or an unarmed arena), which the report marks rather
    than fails."""
    counters, gauges = _rows(path)
    present = any(
        n.startswith(ARENA_PREFIX) for n in (*counters, *gauges)
    )
    lanes: Dict[str, dict] = defaultdict(lambda: {e: 0 for e in ARENA_EVENTS})
    lane_prefix = ARENA_PREFIX + "lane."
    for name, v in counters.items():
        if not name.startswith(lane_prefix):
            continue
        lane, _, event = name[len(lane_prefix):].rpartition(".")
        if lane and event in ARENA_EVENTS:
            lanes[lane][event] = int(v)
    for name, v in gauges.items():
        if name.startswith(lane_prefix):
            lane, _, g = name[len(lane_prefix):].rpartition(".")
            if lane and g in ("bytes", "hbm_bytes_in_use"):
                lanes[lane][g] = int(v)
    return {
        "legacy": not present,
        "totals": {
            e: int(counters.get(ARENA_PREFIX + e, 0)) for e in ARENA_EVENTS
        },
        "bytes": int(gauges.get(ARENA_PREFIX + "bytes", 0)),
        "lanes": dict(sorted(lanes.items())),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("jsonl", help="metrics JSONL from a factor-cache run")
    args = ap.parse_args(argv)

    table, tot, flagged = analyze(args.jsonl)
    if not table:
        print("no serve.factor_cache.fp.* counters in this JSONL "
              "(factor cache off, or no eligible traffic)")
        return 0
    cols = ("hit", "miss", "evict", "invalidate", "update", "stale",
            "spill")
    widths = [max(len(c) + 2, 7) for c in cols]
    hdr = (f"{'fp':14}" + "".join(f"{c:>{w}}" for c, w in zip(cols, widths))
           + f"{'bytes':>11}")
    print(hdr)
    print("-" * len(hdr))
    for r in table:
        print(
            f"{r['fp']:14}"
            + "".join(f"{r[c]:{w}d}" for c, w in zip(cols, widths))
            + f"{r.get('bytes', 0):11d}"
        )
    print(
        "\ntotals: "
        + " ".join(f"{k}={v}" for k, v in sorted(tot.items()) if v)
    )
    arena = analyze_arena(args.jsonl)
    if arena["legacy"]:
        print("\narena: legacy(arena) — no serve.arena.* counters in "
              "this dump (pre-arena JSONL or arena unarmed)")
    else:
        acols = ("hit", "miss", "upload_avoided_bytes", "upload_bytes",
                 "cross_replica", "spill", "evict")
        print("\narena (device-resident factors):")
        awidths = [max(len(c) + 2, 7) for c in acols]
        ahdr = (f"{'lane':14}"
                + "".join(f"{c:>{w}}" for c, w in zip(acols, awidths))
                + f"{'bytes':>11}{'hbm_in_use':>12}")
        print(ahdr)
        print("-" * len(ahdr))
        for lane, row in arena["lanes"].items():
            print(
                f"{lane:14}"
                + "".join(f"{row.get(c, 0):{w}d}"
                          for c, w in zip(acols, awidths))
                + f"{row.get('bytes', 0):11d}"
                + f"{row.get('hbm_bytes_in_use', 0):12d}"
            )
        atot = arena["totals"]
        print("arena totals: "
              + " ".join(f"{k}={v}" for k, v in sorted(atot.items()) if v)
              + f" resident_bytes={arena['bytes']}")
    if flagged:
        print(
            "\nFLAG: repeated-A stream (same fingerprint missed >= 2x "
            "with no evict/invalidate pressure) recorded ZERO hits — "
            "the factor cache is configured but not serving"
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
