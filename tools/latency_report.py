#!/usr/bin/env python
"""Per-bucket/per-replica latency percentile table from a metrics JSONL.

    python tools/latency_report.py out.jsonl [--p99-budget 0.5]

Rows come from the ``serve.latency.*`` histograms the SolverService
records (slate_tpu/serve/service.py): per bucket label, the
**queued** (admit -> dispatch, coalesce window included), **execute**
(padded-batch dispatch wall) and **total** (admit -> deliver) splits;
per replica lane, the total.  Histogram JSONL lines carry
count/min/max/p50/p95/p99 plus the nonzero ``[le, count]`` bucket rows
on the fixed log lattice (``metrics.HIST_EDGES``), so any other
percentile can be re-ranked from the same dump.

Underneath the table: the deadline-budget burn tiers
(``serve.slo_burn.*``) and the head-of-line age gauges
(``serve.replica.<i>.oldest_queued_s``).

The ``peak(MB)`` column joins the device-telemetry registry
(``SLATE_TPU_DEVMON=1``): each bucket's build-time
``memory_analysis`` peak bytes (max over its batch points), so one
table answers "slow because big" vs "slow because cold" — a fat p99
beside a fat peak is a capacity problem, beside a slim one it is a
queueing/compile problem.  ``-`` when the run captured no registry.

Exit status is the **SLO verdict**: with ``--p99-budget S``, any
bucket whose total p99 exceeds ``S`` seconds exits nonzero (what the
``run_tests.py --latency`` gate fails on), as does a JSONL with no
latency histograms at all (a budget over no data verifies nothing).

Produce the JSONL with ``SLATE_TPU_METRICS=out.jsonl`` around any
serving workload (examples/ex21_tracing.py shows the loop).
"""

import argparse
import json
import re
import sys

_LAT_RE = re.compile(
    r"^serve\.latency\.(?P<scope>.+)\.(?P<split>queued|execute|total)$"
)
_COST_RE = re.compile(r"^serve\.(?P<bucket>.+)\.b(?P<batch>\d+)$")

SPLITS = ("queued", "execute", "total")


def load_records(path):
    hists, counters, gauges, peaks = {}, {}, {}, {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            r = json.loads(line)
            # cumulative snapshots: last value wins (same semantics as
            # the sibling reports — summing re-dumped JSONLs inflates)
            if r.get("type") == "hist":
                hists[r["name"]] = r
            elif r.get("type") == "counter":
                counters[r["name"]] = r.get("value", 0)
            elif r.get("type") == "gauge":
                gauges[r["name"]] = r.get("value", 0)
            elif r.get("type") == "cost":
                # registry peak bytes per bucket label: max over the
                # label's batch points (the memory column's join key)
                m = _COST_RE.match(r.get("name", ""))
                if m and r.get("peak_bytes"):
                    lbl = m.group("bucket")
                    peaks[lbl] = max(peaks.get(lbl, 0),
                                     int(r["peak_bytes"]))
    return hists, counters, gauges, peaks


def latency_rows(hists):
    """{scope: {split: hist-record}}; scope is a bucket label or
    ``replica.<name>``."""
    rows = {}
    for name, rec in hists.items():
        m = _LAT_RE.match(name)
        if not m:
            continue
        rows.setdefault(m.group("scope"), {})[m.group("split")] = rec
    return rows


def _ms(rec, field):
    if rec is None:
        return "-"
    return f"{rec[field] * 1e3:.1f}"


def main(argv=None):
    ap = argparse.ArgumentParser(prog="latency_report")
    ap.add_argument("jsonl", help="metrics JSONL (SLATE_TPU_METRICS output)")
    ap.add_argument("--p99-budget", type=float, default=None,
                    help="SLO verdict: fail when any bucket's total p99 "
                         "exceeds this many seconds")
    args = ap.parse_args(argv)

    hists, counters, gauges, peaks = load_records(args.jsonl)
    rows = latency_rows(hists)
    buckets = {
        s: r for s, r in rows.items()
        if not s.startswith(("replica.", "tenant."))
    }
    replicas = {s: r for s, r in rows.items() if s.startswith("replica.")}
    # per-tenant scopes (admission plane) are rendered separately and
    # NOT judged by the bucket p99 budget: tenant fairness has its own
    # verdict tool (tenant_report.py) — mixing them here would fail a
    # bucket SLO gate on a tenant whose mix concentrates the slow tail
    tenants = {s: r for s, r in rows.items() if s.startswith("tenant.")}

    if not rows:
        print("(no serve.latency.* histograms in this JSONL — did the "
              "stream go through a SolverService with metrics on?)")
        return 1 if args.p99_budget is not None else 0

    hdr = (f"{'bucket':38} {'count':>6} {'queued p50/p99':>15} "
           f"{'exec p50/p99':>15} {'total p50':>10} {'p95':>8} "
           f"{'p99(ms)':>8} {'peak(MB)':>9}")
    print(hdr)
    print("-" * len(hdr))
    over = []
    for scope in sorted(buckets):
        r = buckets[scope]
        total = r.get("total")
        q, x = r.get("queued"), r.get("execute")
        count = (total or q or x or {}).get("count", 0)
        pk = peaks.get(scope)
        print(
            f"{scope:38} {count:6d} "
            f"{_ms(q, 'p50'):>7}/{_ms(q, 'p99'):>7} "
            f"{_ms(x, 'p50'):>7}/{_ms(x, 'p99'):>7} "
            f"{_ms(total, 'p50'):>10} {_ms(total, 'p95'):>8} "
            f"{_ms(total, 'p99'):>8} "
            f"{f'{pk / 1e6:.2f}' if pk else '-':>9}"
        )
        if (args.p99_budget is not None and total is not None
                and total["p99"] > args.p99_budget):
            over.append((scope, total["p99"]))

    if tenants:
        print()
        hdr = (f"{'tenant':>16} {'count':>6} {'total p50':>10} "
               f"{'p95':>8} {'p99(ms)':>8}")
        print(hdr)
        print("-" * len(hdr))
        for scope in sorted(tenants):
            t = tenants[scope].get("total")
            print(
                f"{scope.split('.', 1)[1]:>16} "
                f"{(t or {}).get('count', 0):6d} "
                f"{_ms(t, 'p50'):>10} {_ms(t, 'p95'):>8} "
                f"{_ms(t, 'p99'):>8}"
            )

    if replicas:
        print()
        hdr = (f"{'replica':>10} {'count':>6} {'total p50':>10} "
               f"{'p95':>8} {'p99(ms)':>8} {'oldest_queued_s':>16}")
        print(hdr)
        print("-" * len(hdr))
        for scope in sorted(replicas):
            t = replicas[scope].get("total")
            name = scope.split(".", 1)[1]
            oldest = gauges.get(f"serve.replica.{name}.oldest_queued_s")
            print(
                f"{name:>10} {(t or {}).get('count', 0):6d} "
                f"{_ms(t, 'p50'):>10} {_ms(t, 'p95'):>8} "
                f"{_ms(t, 'p99'):>8} "
                f"{oldest if oldest is not None else '-':>16}"
            )

    # the adaptive-window trajectory (admission plane, SLATE_TPU_ADAPTIVE):
    # final window per bucket + how many AIMD decisions moved it — the
    # controller's footprint on the percentiles above
    adaptive = {
        name[len("serve.adaptive."):-len(".window_s")]: v
        for name, v in gauges.items()
        if name.startswith("serve.adaptive.") and name.endswith(".window_s")
    }
    if adaptive:
        chg = {}
        for name, v in counters.items():
            if name.startswith("serve.adaptive.") and (
                name.endswith(".widen") or name.endswith(".shrink")
            ):
                b = name[len("serve.adaptive."):].rsplit(".", 1)[0]
                chg[b] = chg.get(b, 0) + int(v)
        print("\nadaptive window per bucket:")
        for b in sorted(adaptive):
            print(f"  {b:40} {adaptive[b] * 1e3:8.3f} ms "
                  f"({chg.get(b, 0)} changes)")

    burn = {k.rsplit(".", 1)[1]: int(v) for k, v in counters.items()
            if k.startswith("serve.slo_burn.")}
    if burn:
        total_b = burn.get("requests", 0)
        tiers = ", ".join(f"{k}={v}" for k, v in sorted(burn.items())
                          if k != "requests")
        print(f"\nslo burn (of {total_b} deadline requests): "
              + (tiers or "all under 50% of budget"))

    if over:
        for scope, p99 in over:
            print(f"FAIL: {scope} total p99 {p99 * 1e3:.1f} ms exceeds "
                  f"the {args.p99_budget * 1e3:.1f} ms budget")
        return 1
    if args.p99_budget is not None:
        print(f"\np99 budget ok: every bucket under "
              f"{args.p99_budget * 1e3:.1f} ms")
    return 0


if __name__ == "__main__":
    sys.exit(main())
