#!/usr/bin/env python
"""slate-lint CLI: run the AST invariant checker over the tree.

    python tools/slate_lint.py                 # full tree, text report
    python tools/slate_lint.py --json          # machine-readable
    python tools/slate_lint.py --rules env-drift,metric-drift
    python tools/slate_lint.py --list          # rule table
    python tools/slate_lint.py --write-baseline  # accept current findings

Exit status: 0 when no *new* findings (suppressed and baselined ones
never fail the run), 1 otherwise.  ``run_tests.py --lint`` wraps this
with a runtime budget for CI.

The checker is ``slate_tpu/analysis/`` — stdlib ``ast`` only, no jax
import, so it runs in milliseconds-per-file on any box.  See the
README "Static analysis" section for the rule table, the suppression
workflow (``# slate-lint: disable=<rule>``), and how to add a rule.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_analysis():
    """Load ``slate_tpu/analysis`` WITHOUT executing ``slate_tpu``'s
    package ``__init__`` (which imports jax and the full library): the
    linter must keep working — and keep reporting parse errors as
    findings — when the tree it checks is import-broken."""
    name = "slate_lint_analysis"
    if name in sys.modules:
        return sys.modules[name]
    pkg_dir = os.path.join(_ROOT, "slate_tpu", "analysis")
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(pkg_dir, "__init__.py"),
        submodule_search_locations=[pkg_dir],
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


analysis = _load_analysis()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--root", default=os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))
        ),
        help="repo root to lint (default: this checkout)",
    )
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule subset (default: all)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the JSON report instead of text")
    ap.add_argument("--list", action="store_true", dest="list_rules",
                    help="list registered rules and exit")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline file (default: <root>/"
                         f"{analysis.BASELINE_NAME})")
    ap.add_argument("--write-baseline", action="store_true",
                    help="accept the run's findings as the new baseline")
    ap.add_argument("--write-lock-graph", action="store_true",
                    help="regenerate the checked-in lock-order graph "
                         f"artifact (<root>/{analysis.LOCK_GRAPH_NAME}) "
                         "from the current tree")
    args = ap.parse_args(argv)

    if args.list_rules:
        for name in sorted(analysis.RULES):
            r = analysis.RULES[name]
            print(f"{name:18} {r.summary}")
        return 0

    if args.write_lock_graph:
        loaded = analysis.core.load_project(args.root)
        path = analysis.races.write_graph_artifact(
            args.root, loaded.project
        )
        n = len(analysis.races.lock_graph(loaded.project))
        print(f"lock-order graph written: {path} ({n} edge(s))")
        return 0

    if args.write_baseline and args.rules:
        print("refusing --write-baseline with --rules: a partial run "
              "would overwrite (and truncate) the other rules' accepted "
              "fingerprints", file=sys.stderr)
        return 2

    rules = None
    if args.rules:
        rules = [r.strip() for r in args.rules.split(",") if r.strip()]
        unknown = [r for r in rules if r not in analysis.RULES]
        if unknown:
            print(f"unknown rule(s): {', '.join(unknown)}; "
                  f"known: {', '.join(sorted(analysis.RULES))}",
                  file=sys.stderr)
            return 2

    baseline_path = args.baseline or os.path.join(
        args.root, analysis.BASELINE_NAME
    )
    baseline = analysis.load_baseline(baseline_path)
    result = analysis.run(args.root, rules=rules, baseline=baseline)

    if args.write_baseline:
        analysis.write_baseline(baseline_path, result)
        print(f"baseline written: {baseline_path} "
              f"({len(result.all_with_fingerprints)} fingerprint(s))")
        return 0

    if args.as_json:
        print(json.dumps(result.to_json(), indent=2))
    else:
        print(result.render())
    return 0 if result.ok else 1


if __name__ == "__main__":
    sys.exit(main())
