#!/usr/bin/env python
"""Perf regression sentinel: diff two bench JSON artifacts and fail on
GFLOP/s regressions or peak-memory growth past thresholds.

    python tools/bench_diff.py BENCH_r03.json BENCH_r04.json
    python tools/bench_diff.py --baseline BENCH_r04.json live.json
    python tools/bench_diff.py --floor BENCH_FLOOR_CPU.json live.json

Accepts either shape of bench artifact: the raw ``bench.py`` stdout
line (``{"metric", "value", "extra", ...}``) or the driver's recorded
wrapper (``{"rc", "tail", "parsed": {...}}`` — the checked-in
``BENCH_r*.json`` trajectory).  Compared fields, per ``extra`` entry
and for the headline ``value``:

* **rates** (higher is better): ``gflops``, ``requests_per_s`` — a
  candidate below ``baseline * (1 - --max-drop)`` is a regression;
* **memory** (lower is better): ``peak_bytes`` — a candidate above
  ``baseline * (1 + --max-mem-growth)`` is growth past threshold;
* **latency** (lower is better): ``p99_s`` — a candidate above
  ``baseline * (1 + --max-lat-growth)`` is a tail regression (the
  ``soak_sustained`` entry's client-observed p99; in ``--floor`` mode
  the baseline value is a hard ceiling).

``--floor`` switches to absolute-floor semantics: the baseline file's
rate values are hard minimums and its ``peak_bytes`` values hard
ceilings (no fractional slack) — the shape of a checked-in floor file
(``BENCH_FLOOR_CPU.json``) deliberately set far below any healthy run,
so the ``run_tests.py --perf`` gate is robust across machines while a
real collapse (a serialization bug, an accidental O(n^4) path, a
donation regression doubling copies) still trips it.

Entries marked ``skipped`` or ``error`` on either side are reported
and excluded (a partial sweep must stay diagnosable, not auto-fail);
``--require-all`` makes a baseline entry missing from the candidate a
failure.  Exit status: 0 = no regression, 1 = regression/growth, 2 =
unusable input.
"""

import argparse
import json
import sys

RATE_FIELDS = ("gflops", "requests_per_s")
MEM_FIELDS = ("peak_bytes",)
LAT_FIELDS = ("p99_s",)


def load_bench(path):
    """The ``{"metric", "value", "extra"}`` payload of either artifact
    shape; None when the file is missing/unreadable/not JSON or has no
    parsed bench line (e.g. a sweep that died before printing —
    BENCH_r05) — every unusable input maps to exit code 2, never to
    the regression verdict."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        print(f"bench_diff: cannot read {path}: "
              f"{type(e).__name__}: {e}")
        return None
    if not isinstance(doc, dict):  # bare null / number / list
        return None
    if "parsed" in doc:
        doc = doc["parsed"]
    if not isinstance(doc, dict) or "extra" not in doc:
        return None
    return doc


def entry_state(entry):
    """Why an entry is (not) comparable: ``"ok"`` carries numbers;
    ``"skipped"``/``"error"`` are bench's recorded non-results;
    ``"malformed"`` is anything that is not a dict at all (a
    hand-edited floor file, a partially-written sweep) — reported,
    never crashed on."""
    if not isinstance(entry, dict):
        return "malformed"
    if "skipped" in entry:
        return "skipped"
    if "error" in entry:
        return "error"
    return "ok"


def main(argv=None):
    ap = argparse.ArgumentParser(prog="bench_diff")
    ap.add_argument("baseline_pos", nargs="?", default=None,
                    metavar="baseline", help="baseline bench JSON")
    ap.add_argument("candidate", help="candidate bench JSON (live run "
                                      "or a later BENCH_r*.json)")
    ap.add_argument("--baseline", dest="baseline_opt", default=None,
                    help="baseline bench JSON (alternative spelling "
                         "for live-vs-baseline runs)")
    ap.add_argument("--max-drop", type=float, default=0.30,
                    help="allowed fractional rate drop before a "
                         "regression verdict (default 0.30)")
    ap.add_argument("--max-mem-growth", type=float, default=0.50,
                    help="allowed fractional peak-memory growth "
                         "(default 0.50)")
    ap.add_argument("--max-lat-growth", type=float, default=1.00,
                    help="allowed fractional p99 latency growth "
                         "(default 1.00 — tails are noisy on shared "
                         "CPU runners)")
    ap.add_argument("--floor", action="store_true",
                    help="baseline values are absolute floors "
                         "(rates) / ceilings (peak_bytes), no "
                         "fractional slack")
    ap.add_argument("--require-all", action="store_true",
                    help="fail when a baseline entry is missing from "
                         "the candidate")
    args = ap.parse_args(argv)

    base_path = args.baseline_opt or args.baseline_pos
    if base_path is None:
        ap.error("a baseline is required (positional or --baseline)")
    base = load_bench(base_path)
    cand = load_bench(args.candidate)
    if base is None or cand is None:
        which = base_path if base is None else args.candidate
        print(f"bench_diff: {which} carries no parsed bench payload "
              "(sweep died before its JSON line?)")
        return 2

    regress, notes = [], []
    compared = [0]  # comparisons actually made: zero proves nothing

    def check_rate(label, field, old, new):
        compared[0] += 1
        floor = old if args.floor else old * (1.0 - args.max_drop)
        ok = new >= floor
        verdict = "ok" if ok else "REGRESSION"
        delta = (new - old) / old * 100.0 if old else float("inf")
        print(f"{label:40} {field:>14} {old:>12.1f} -> {new:>12.1f} "
              f"({delta:+6.1f}%) {verdict}")
        if not ok:
            regress.append(
                f"{label}.{field}: {new:.1f} below "
                + (f"floor {floor:.1f}" if args.floor
                   else f"{old:.1f} - {args.max_drop * 100:.0f}%")
            )

    def check_mem(label, field, old, new):
        compared[0] += 1
        ceil = old if args.floor else old * (1.0 + args.max_mem_growth)
        ok = new <= ceil
        verdict = "ok" if ok else "MEM GROWTH"
        delta = (new - old) / old * 100.0 if old else float("inf")
        print(f"{label:40} {field:>14} {old:>12.0f} -> {new:>12.0f} "
              f"({delta:+6.1f}%) {verdict}")
        if not ok:
            regress.append(
                f"{label}.{field}: {new:.0f} above "
                + (f"ceiling {ceil:.0f}" if args.floor
                   else f"{old:.0f} + {args.max_mem_growth * 100:.0f}%")
            )

    def check_lat(label, field, old, new):
        compared[0] += 1
        ceil = old if args.floor else old * (1.0 + args.max_lat_growth)
        ok = new <= ceil
        verdict = "ok" if ok else "LAT GROWTH"
        delta = (new - old) / old * 100.0 if old else float("inf")
        print(f"{label:40} {field:>14} {old:>12.4f} -> {new:>12.4f} "
              f"({delta:+6.1f}%) {verdict}")
        if not ok:
            regress.append(
                f"{label}.{field}: {new:.4f} above "
                + (f"ceiling {ceil:.4f}" if args.floor
                   else f"{old:.4f} + {args.max_lat_growth * 100:.0f}%")
            )

    hdr = (f"{'entry':40} {'field':>14} {'baseline':>12}    "
           f"{'candidate':>12}")
    print(hdr)
    print("-" * len(hdr))
    if isinstance(base.get("value"), (int, float)) and isinstance(
        cand.get("value"), (int, float)
    ):
        # the headline is comparable only when both sides measured the
        # SAME metric — a CPU --quick run vs a TPU trajectory file
        # carries different headline names (sgemm_n512 vs sgemm_n8192)
        # and a -99% "regression" there would be pure shape noise
        if base.get("metric") == cand.get("metric"):
            check_rate("(headline)", base.get("metric", "value"),
                       float(base["value"]), float(cand["value"]))
        else:
            notes.append(
                f"headline metrics differ ({base.get('metric')} vs "
                f"{cand.get('metric')}); not compared"
            )

    bex, cex = base.get("extra") or {}, cand.get("extra") or {}
    if not isinstance(bex, dict) or not isinstance(cex, dict):
        print("bench_diff: 'extra' is not an entry map")
        return 2
    for label in sorted(bex):
        be, ce = bex[label], cex.get(label)
        bstate = entry_state(be)
        if bstate != "ok":
            notes.append(f"{label}: baseline entry {bstate}")
            continue
        cstate = "missing" if ce is None else entry_state(ce)
        if cstate != "ok":
            msg = f"{label}: candidate entry {cstate}"
            notes.append(msg)
            if args.require_all:
                regress.append(msg)
            continue
        for field in RATE_FIELDS:
            if field in be and field in ce:
                check_rate(label, field, float(be[field]),
                           float(ce[field]))
        for field in MEM_FIELDS:
            if field in be and field in ce:
                check_mem(label, field, float(be[field]),
                          float(ce[field]))
        for field in LAT_FIELDS:
            # p99_s is None when a run delivered nothing (all shed) —
            # nothing to compare, not a crash
            if be.get(field) is not None and ce.get(field) is not None:
                check_lat(label, field, float(be[field]),
                          float(ce[field]))

    for n in notes:
        print(f"note: {n}")
    if regress:
        print(f"\nFAIL: {len(regress)} regression(s):")
        for r in regress:
            print(f"  {r}")
        return 1
    if not compared[0]:
        # an all-skipped/errored sweep (or two files sharing no
        # comparable fields) verified NOTHING — that is unusable
        # input, never a clean bill of health
        print("\nbench_diff: no comparable fields between the two "
              "artifacts — nothing was verified")
        return 2
    mode = "floor" if args.floor else f"drop<{args.max_drop * 100:.0f}%"
    print(f"\nbench_diff ok ({mode}): {compared[0]} comparison(s), no "
          "regression, no memory growth past threshold")
    return 0


if __name__ == "__main__":
    sys.exit(main())
