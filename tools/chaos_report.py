#!/usr/bin/env python
"""Injected-vs-recovered report over a metrics JSONL.

Reads a ``SLATE_TPU_METRICS`` dump from a chaos run (faults armed via
``SLATE_TPU_FAULTS`` or ``aux.faults``) and joins every
``faults.injected.<site>`` counter against the serve hardening
counters that should have absorbed it.  The site -> recovery-counter
map is DERIVED from ``slate_tpu/aux/faults.py``'s ``SITE_SPECS``
registry — the single source of truth, where each site's rationale
comment lives (``python tools/slate_lint.py --rules fault-site``
checks it against the emitters).  The registry file is AST-parsed,
not imported, so this tool stays stdlib-only and keeps working when
the library itself is broken — which is exactly when a chaos triage
tool gets reached for.

For the artifact sites the detection counter IS the containment
signal: an injected corruption that the verification ladder counted
was, by construction, degraded to a recompile instead of loaded
(serve/artifacts.py); an injection with no detection means a bad
artifact was served unverified.

A site with injections but NO recovery signal is flagged — either the
containment path regressed or the site is not wired to one — and the
tool exits nonzero so CI can gate on it.  Exception: ``latency`` is
informational only (reported, never flagged) — added delay violates
nothing unless requests carry deadlines, so a latency-only run with no
deadline traffic is a legitimate zero-signal outcome.

Attribution caveat: the counters are process-global, so when two armed
sites share a recovery family (``compile`` and ``execute`` both join
``serve.retries``/``serve.fallbacks``), one site's activity can mask
the other's regressed containment.  Rows whose every signal is shared
with another injected site are marked ``shared with <site>`` — for
airtight per-site attribution, run one site per chaos pass.

Usage:
    SLATE_TPU_METRICS=/tmp/chaos.jsonl python -m pytest tests/test_chaos.py
    python tools/chaos_report.py /tmp/chaos.jsonl
"""

from __future__ import annotations

import argparse
import ast
import json
import os
import sys
from typing import Dict, List

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_FAULTS_PY = os.path.join(_REPO_ROOT, "slate_tpu", "aux", "faults.py")


def _load_registry(path: str = _FAULTS_PY) -> Dict[str, dict]:
    """AST-parse the ``SiteSpec(...)`` entries out of aux/faults.py
    using the ONE shared extractor
    (``slate_tpu/analysis/rules_faults.parse_site_specs`` — the same
    code the ``fault-site`` lint rule runs).  The analysis package is
    loaded by file path, never through ``slate_tpu/__init__``, so this
    tool stays stdlib-only and library-import-free."""
    import importlib.util

    name = "slate_lint_analysis"
    mod = sys.modules.get(name)
    if mod is None:
        pkg_dir = os.path.join(_REPO_ROOT, "slate_tpu", "analysis")
        spec = importlib.util.spec_from_file_location(
            name, os.path.join(pkg_dir, "__init__.py"),
            submodule_search_locations=[pkg_dir],
        )
        mod = importlib.util.module_from_spec(spec)
        sys.modules[name] = mod
        spec.loader.exec_module(mod)
    tree = ast.parse(open(path, encoding="utf-8").read(), filename=path)
    specs = mod.rules_faults.parse_site_specs(tree)
    if not specs:
        raise RuntimeError(f"no SiteSpec registry found in {path}")
    return {
        s.name: {"recovery": s.recovery, "informational": s.informational}
        for s in specs.values()
    }


# site -> counter families whose sum is that site's recovery signal,
# and the sites whose zero-recovery outcome is legitimate.  Both are
# DERIVED from aux/faults.py's SITE_SPECS registry — the single source
# of truth shared with arm()'s site validation and the `fault-site`
# lint rule — so a site added there is automatically joined here.
# Loaded LAZILY (module __getattr__ / first analyze()): `--help` and a
# bad-usage error must not depend on the registry file parsing.
_REGISTRY_CACHE: Dict[str, dict] = {}


def _registry() -> Dict[str, dict]:
    if not _REGISTRY_CACHE:
        _REGISTRY_CACHE.update(_load_registry())
    return _REGISTRY_CACHE


def __getattr__(name: str):
    # PEP 562: keep RECOVERY/INFORMATIONAL as importable module attrs
    # (tests assert parity against the library registry) without an
    # import-time parse
    if name == "RECOVERY":
        return {n: s["recovery"] for n, s in _registry().items()}
    if name == "INFORMATIONAL":
        return {n for n, s in _registry().items() if s["informational"]}
    raise AttributeError(name)

INJECT_PREFIX = "faults.injected."


def _counters(path: str) -> Dict[str, float]:
    out: Dict[str, float] = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            row = json.loads(line)
            if row.get("type") == "counter":
                out[row["name"]] = float(row.get("value", 0))
    return out


def analyze(path: str) -> List[dict]:
    """One row per injected site: injected count, summed recovery
    signal, the counters it came from, and the flag."""
    counters = _counters(path)
    registry = _registry()
    recovery = {n: s["recovery"] for n, s in registry.items()}
    informational = {n for n, s in registry.items() if s["informational"]}
    injected_sites = {
        name[len(INJECT_PREFIX):]
        for name, v in counters.items()
        if name.startswith(INJECT_PREFIX) and v > 0
    }
    rows = []
    for site in sorted(injected_sites):
        injected = counters[INJECT_PREFIX + site]
        families = recovery.get(site, ())
        signals = {f: counters[f] for f in families if counters.get(f, 0) > 0}
        recovered = sum(signals.values())
        # every nonzero signal also claimable by another injected site
        # => this row's recovery cannot be attributed to this site alone
        sharers = sorted(
            o for o in injected_sites
            if o != site and signals
            and all(f in recovery.get(o, ()) for f in signals)
        )
        rows.append({
            "site": site,
            "injected": int(injected),
            "recovered": int(recovered),
            "signals": signals,
            "shared_with": sharers,
            "flagged": recovered <= 0 and site not in informational,
        })
    return rows


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("jsonl", help="metrics JSONL from a chaos run")
    args = ap.parse_args(argv)

    try:
        _registry()
    except (OSError, SyntaxError, RuntimeError) as e:
        print(f"chaos_report: cannot derive the site registry from "
              f"{_FAULTS_PY}: {e}", file=sys.stderr)
        return 2
    rows = analyze(args.jsonl)
    if not rows:
        print("no faults.injected.* counters in this JSONL (faults off?)")
        return 0
    hdr = f"{'site':18} {'injected':>9} {'recovered':>10}  status / signals"
    print(hdr)
    print("-" * len(hdr))
    flagged = 0
    for r in rows:
        if r["flagged"]:
            flagged += 1
            status = "FLAG: no recovery/fallback signal"
        elif not r["signals"]:
            status = "informational (no deadline traffic)"
        else:
            status = ", ".join(
                f"{k}={int(v)}" for k, v in sorted(r["signals"].items())
            )
            if r["shared_with"]:
                status += f"  [shared with {', '.join(r['shared_with'])}]"
        print(f"{r['site']:18} {r['injected']:9d} {r['recovered']:10d}  {status}")
    if flagged:
        print(f"\n{flagged} site(s) injected faults with no recovery signal")
        return 1
    print("\nevery injected site shows a recovery signal")
    return 0


if __name__ == "__main__":
    sys.exit(main())
