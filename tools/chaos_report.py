#!/usr/bin/env python
"""Injected-vs-recovered report over a metrics JSONL.

Reads a ``SLATE_TPU_METRICS`` dump from a chaos run (faults armed via
``SLATE_TPU_FAULTS`` or ``aux.faults``) and joins every
``faults.injected.<site>`` counter against the serve hardening
counters that should have absorbed it:

    compile        -> serve.fallbacks, serve.retries
    execute        -> serve.retries, serve.fallbacks, serve.breaker_open
    result_corrupt -> serve.corrupt_result, serve.fallbacks
    latency        -> serve.deadline_miss_late
    worker_death   -> serve.worker_restarts
    info_nonzero   -> serve.numerical_errors
    artifact_corrupt   -> serve.artifact_corrupt
    artifact_stale     -> serve.artifact_stale
    artifact_load_fail -> serve.artifact_load_fail
    factor_stale       -> serve.factor_cache.stale
    tenant_flood       -> serve.shed, serve.rejected_quota,
                          serve.rejected_share, serve.rejected

For the artifact sites the detection counter IS the containment
signal: an injected corruption that the verification ladder counted
was, by construction, degraded to a recompile instead of loaded
(serve/artifacts.py); an injection with no detection means a bad
artifact was served unverified.

A site with injections but NO recovery signal is flagged — either the
containment path regressed or the site is not wired to one — and the
tool exits nonzero so CI can gate on it.  Exception: ``latency`` is
informational only (reported, never flagged) — added delay violates
nothing unless requests carry deadlines, so a latency-only run with no
deadline traffic is a legitimate zero-signal outcome.

Attribution caveat: the counters are process-global, so when two armed
sites share a recovery family (``compile`` and ``execute`` both join
``serve.retries``/``serve.fallbacks``), one site's activity can mask
the other's regressed containment.  Rows whose every signal is shared
with another injected site are marked ``shared with <site>`` — for
airtight per-site attribution, run one site per chaos pass.

Usage:
    SLATE_TPU_METRICS=/tmp/chaos.jsonl python -m pytest tests/test_chaos.py
    python tools/chaos_report.py /tmp/chaos.jsonl
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List

#: site -> counter families whose sum is that site's recovery signal
RECOVERY = {
    "compile": ("serve.fallbacks", "serve.retries"),
    "execute": ("serve.retries", "serve.fallbacks", "serve.breaker_open"),
    # the per-item direct re-solve of a corrupt batch bumps
    # serve.fallbacks, so it is part of this site's signal (and of the
    # shared-attribution overlap with compile/execute)
    "result_corrupt": ("serve.corrupt_result", "serve.fallbacks"),
    # _miss_late() bumps both the split counter and the total; summing
    # them would double-count, so only the split counter is joined
    "latency": ("serve.deadline_miss_late",),
    "worker_death": ("serve.worker_restarts",),
    "info_nonzero": ("serve.numerical_errors",),
    # detection == containment for the artifact load ladder: a counted
    # rung means the bad artifact was recompiled, not served
    "artifact_corrupt": ("serve.artifact_corrupt",),
    "artifact_stale": ("serve.artifact_stale",),
    "artifact_load_fail": ("serve.artifact_load_fail",),
    # detection == containment for the factor-cache hit path too: a
    # counted stale means the residual validation caught the mismatched
    # factor and the item was re-solved direct, never delivered wrong
    "factor_stale": ("serve.factor_cache.stale",),
    # a synthetic tenant burst is absorbed when the admission plane
    # refused (some of) it: overload shedding, token-bucket/queue-share
    # quota rejections, or plain bounded-queue backpressure — a flood
    # with NO refusal signal means fairness never engaged and the
    # burst rode straight into the shared queue
    "tenant_flood": (
        "serve.shed", "serve.rejected_quota", "serve.rejected_share",
        "serve.rejected",
    ),
}

#: sites whose zero-recovery outcome is legitimate (see module doc)
INFORMATIONAL = {"latency"}

INJECT_PREFIX = "faults.injected."


def _counters(path: str) -> Dict[str, float]:
    out: Dict[str, float] = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            row = json.loads(line)
            if row.get("type") == "counter":
                out[row["name"]] = float(row.get("value", 0))
    return out


def analyze(path: str) -> List[dict]:
    """One row per injected site: injected count, summed recovery
    signal, the counters it came from, and the flag."""
    counters = _counters(path)
    injected_sites = {
        name[len(INJECT_PREFIX):]
        for name, v in counters.items()
        if name.startswith(INJECT_PREFIX) and v > 0
    }
    rows = []
    for site in sorted(injected_sites):
        injected = counters[INJECT_PREFIX + site]
        families = RECOVERY.get(site, ())
        signals = {f: counters[f] for f in families if counters.get(f, 0) > 0}
        recovered = sum(signals.values())
        # every nonzero signal also claimable by another injected site
        # => this row's recovery cannot be attributed to this site alone
        sharers = sorted(
            o for o in injected_sites
            if o != site and signals
            and all(f in RECOVERY.get(o, ()) for f in signals)
        )
        rows.append({
            "site": site,
            "injected": int(injected),
            "recovered": int(recovered),
            "signals": signals,
            "shared_with": sharers,
            "flagged": recovered <= 0 and site not in INFORMATIONAL,
        })
    return rows


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("jsonl", help="metrics JSONL from a chaos run")
    args = ap.parse_args(argv)

    rows = analyze(args.jsonl)
    if not rows:
        print("no faults.injected.* counters in this JSONL (faults off?)")
        return 0
    hdr = f"{'site':18} {'injected':>9} {'recovered':>10}  status / signals"
    print(hdr)
    print("-" * len(hdr))
    flagged = 0
    for r in rows:
        if r["flagged"]:
            flagged += 1
            status = "FLAG: no recovery/fallback signal"
        elif not r["signals"]:
            status = "informational (no deadline traffic)"
        else:
            status = ", ".join(
                f"{k}={int(v)}" for k, v in sorted(r["signals"].items())
            )
            if r["shared_with"]:
                status += f"  [shared with {', '.join(r['shared_with'])}]"
        print(f"{r['site']:18} {r['injected']:9d} {r['recovered']:10d}  {status}")
    if flagged:
        print(f"\n{flagged} site(s) injected faults with no recovery signal")
        return 1
    print("\nevery injected site shows a recovery signal")
    return 0


if __name__ == "__main__":
    sys.exit(main())
