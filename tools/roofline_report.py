#!/usr/bin/env python
"""Roofline attribution table from a metrics JSONL: achieved GFLOP/s,
arithmetic intensity, and the compute- vs memory-bound verdict per
warmed serve bucket (Williams, Waterman & Patterson, CACM 2009 —
PAPERS.md).

    python tools/roofline_report.py out.jsonl [--min-frac 0.0]

Joins two record families the device telemetry plane emits
(``SLATE_TPU_DEVMON=1`` + ``SLATE_TPU_METRICS=out.jsonl``):

* ``{"type": "cost", "name": "serve.<bucket>.b<batch>", ...}`` — the
  build-time ``cost_analysis``/``memory_analysis`` registry record
  (flops, bytes accessed, peak bytes, device kind) captured by
  serve/cache.py at every cold build and artifact restore;
* ``{"type": "timer", "name": "serve.<bucket>.b<batch>.run", ...}`` —
  the steady-state dispatch wall the cache's instrumented executables
  record (compile wall is excluded by construction).

Per warmed executable: achieved FLOP/s = registry flops / mean run
wall; intensity = flops / bytes accessed; the verdict compares
intensity against the device ridge point from the peaks table
(``aux/devmon.DEFAULT_PEAKS``; override per deployment with
``SLATE_TPU_PEAKS='{"cpu": {"flops": 5e10, "bytes_per_s": 2e10}}'``).
This is the measured form of the ROADMAP item-1 claim — whether the
panel/small-tile buckets, not the trailing gemms, bound the recursive
schedules is read off the bound column, not asserted.

Exit status is the gate verdict (``run_tests.py --perf``): nonzero
when the JSONL has no registry cost rows at all, or when any WARMED
bucket (one with run dispatches) is unclassifiable — no cost record,
or flops/bytes the roofline cannot rate.  ``--min-frac F`` further
fails any warmed bucket achieving less than ``F`` of its roof.
"""

import argparse
import json
import os
import re
import sys

_RUN_RE = re.compile(r"^serve\.(?P<exe>.+\.b\d+)\.run$")
_COST_RE = re.compile(r"^serve\.(?P<exe>.+\.b\d+)$")


def load_records(path):
    costs, runs = {}, {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            r = json.loads(line)
            # cumulative snapshots: last value wins (same rule as the
            # sibling reports — summing re-dumped JSONLs inflates)
            if r.get("type") == "cost":
                m = _COST_RE.match(r.get("name", ""))
                if m:
                    costs[m.group("exe")] = r
            elif r.get("type") == "timer":
                m = _RUN_RE.match(r.get("name", ""))
                if m:
                    runs[m.group("exe")] = r
    return costs, runs


def main(argv=None):
    ap = argparse.ArgumentParser(prog="roofline_report")
    ap.add_argument("jsonl", help="metrics JSONL (SLATE_TPU_METRICS "
                                  "output from a SLATE_TPU_DEVMON=1 run)")
    ap.add_argument("--min-frac", type=float, default=None,
                    help="fail any warmed bucket achieving less than "
                         "this fraction of its roof")
    args = ap.parse_args(argv)

    # the peaks table lives in the library (one source of truth with
    # health()/examples); the tool only needs devmon, not jax
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    from slate_tpu.aux import devmon

    costs, runs = load_records(args.jsonl)
    if not costs:
        print("(no serve.* cost records in this JSONL — was the stream "
              "run with SLATE_TPU_DEVMON=1 so the cache captured "
              "cost/memory at build time?)")
        return 1

    kinds = {c.get("device_kind", "unknown") for c in costs.values()}
    peaks = {k: devmon.peaks_for(k) for k in kinds}
    for k in sorted(kinds):
        p = peaks[k]
        print(f"peaks[{k}]: {p['flops'] / 1e9:.1f} GFLOP/s, "
              f"{p['bytes_per_s'] / 1e9:.1f} GB/s, "
              f"ridge {p['ridge']:.2f} flop/B ({p['source']})")
    print()

    hdr = (f"{'executable':46} {'runs':>5} {'mean(ms)':>9} "
           f"{'GFLOP/s':>9} {'src':>5} {'AI(f/B)':>8} {'roof':>9} "
           f"{'%roof':>6} {'peak(MB)':>9} {'bound':>8}")
    print(hdr)
    print("-" * len(hdr))
    bad = []
    under = []
    for exe in sorted(set(costs) | set(runs)):
        cost = costs.get(exe)
        run = runs.get(exe)
        nruns = int(run.get("count", 0)) if run else 0
        warmed = nruns > 0
        pk_mb = (
            f"{cost['peak_bytes'] / 1e6:9.2f}"
            if cost and cost.get("peak_bytes") else "-"
        )
        rl = None
        fsrc = "xla"
        mean_s = (
            float(run.get("total_s", 0.0)) / nruns if warmed else 0.0
        )
        if warmed and cost is not None:
            # vendor custom calls (CPU trsm/getrf) report no XLA flops:
            # fall back to the registry's hand-model count, labeled
            flops = cost.get("flops")
            if not flops or flops <= 0:
                flops, fsrc = cost.get("flops_model"), "model"
            rl = devmon.roofline(
                flops, cost.get("bytes_accessed"), mean_s,
                peaks.get(cost.get("device_kind", "unknown")),
            )
        if rl is None:
            why = (
                "cold (no runs)" if not warmed
                else "NO COST RECORD" if cost is None
                else "UNRATEABLE (flops/bytes missing or <= 0)"
            )
            print(f"{exe:46} {nruns:5d} {'-':>9} {'-':>9} {'-':>5} "
                  f"{'-':>8} {'-':>9} {'-':>6} {pk_mb:>9} {why:>8}")
            if warmed:
                bad.append((exe, why))
            continue
        print(
            f"{exe:46} {nruns:5d} {mean_s * 1e3:9.2f} "
            f"{rl['achieved_gflops']:9.2f} {fsrc:>5} "
            f"{rl['intensity']:8.2f} "
            f"{rl['roof_flops'] / 1e9:9.2f} "
            f"{rl['frac_of_roof'] * 100:5.1f}% {pk_mb:>9} "
            f"{rl['bound']:>8}"
        )
        if args.min_frac is not None and rl["frac_of_roof"] < args.min_frac:
            under.append((exe, rl["frac_of_roof"]))

    rc = 0
    for exe, why in bad:
        print(f"FAIL: warmed bucket {exe} is unclassifiable ({why})")
        rc = 1
    for exe, frac in under:
        print(f"FAIL: {exe} achieved {frac * 100:.1f}% of roof, below "
              f"the {args.min_frac * 100:.1f}% floor")
        rc = 1
    if rc == 0:
        n = sum(1 for e in runs if int(runs[e].get('count', 0)) > 0
                and e in costs)
        print(f"\nroofline ok: {n} warmed bucket(s) classified")
    return rc


if __name__ == "__main__":
    sys.exit(main())
