#!/usr/bin/env python
"""Unified soak verdict over a metrics JSONL from a ``soak.replay`` run.

One tool joins every plane's end-of-run evidence into a single
pass/fail, the way an operator would triage a soak: did every request
come back (delivery completeness), did the books balance (counter
reconciliation), did anything silently wrong reach a client
(integrity escapes), did injected faults all land on a containment
counter (the chaos join), did the tails stay inside budget, did the
steady state stay compile-free, and did every disruption the health
timeline saw recover.

Checks, in verdict order:

* ``soak.submitted`` present and nonzero — otherwise this is not a
  soak JSONL and the tool exits 2 (unusable input, not a failure).
* Delivery completeness: ``soak.submitted == soak.delivered +
  soak.typed_errors + soak.refused`` — exact; every submission is
  accounted for as a result, a typed error, or a synchronous
  admission refusal.  A shortfall is a hang or a dropped future.
* Admission reconciliation: ``serve.requests == soak.submitted -
  soak.refused`` — exact when the replay engine drove all traffic
  after a ``metrics.reset()`` (hedge twins and retries never count as
  admissions).
* Integrity escapes: ``soak.bad_results == 0`` — the replay engine
  residual-checks every delivered X from the OUTSIDE; one escape
  means a finite-but-wrong answer crossed the client boundary.
* Orphan traces: ``soak.orphan_spans == 0`` (when the gauge is
  present) — a trace with no completed request root is a leaked or
  hung request the completeness sum cannot see.
* Injected <= detected: every ``faults.injected.<site>`` counter
  joins the containment counters from aux/faults.py's ``SiteSpec``
  registry, exactly as ``tools/chaos_report.py`` does (the logic is
  imported from it — one join, two tools).
* Tail budgets: p99 (and optionally p95) of every per-bucket
  ``serve.latency.<bucket>.total`` histogram vs ``--p99-budget-ms``;
  per-tenant scopes get their own ``--tenant-p99-budget-ms``.
* Steady state: ``jit.compilations <= --max-compiles`` (default 0 —
  a warmed service must not compile mid-soak).
* Timeline: at least ``--min-timeline-rows`` ``{"type": "timeline"}``
  rows, and every disruption interval the timeline shows (breakers
  open, lanes quarantined, service not ready) must CLOSE before the
  run ends; ``--max-recovery-s`` optionally budgets the longest one.

Usage:
    python tools/soak_report.py /tmp/soak.jsonl
    python tools/soak_report.py /tmp/soak.jsonl --p99-budget-ms 500
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
from typing import Dict, List, Optional, Tuple

_HERE = os.path.dirname(os.path.abspath(__file__))

_LAT_RE = re.compile(
    r"^serve\.latency\.(?P<scope>.+)\.(?P<split>queued|execute|total)$"
)


def _chaos():
    """The sibling chaos_report module (site registry + injected/
    recovered join), loaded by file path so this tool works no matter
    how it was invoked."""
    import importlib.util

    name = "soak_report_chaos"
    mod = sys.modules.get(name)
    if mod is None:
        spec = importlib.util.spec_from_file_location(
            name, os.path.join(_HERE, "chaos_report.py")
        )
        mod = importlib.util.module_from_spec(spec)
        sys.modules[name] = mod
        spec.loader.exec_module(mod)
    return mod


def load(path: str) -> dict:
    """Counters/gauges/hists (cumulative snapshots: last value wins,
    same as every sibling report) plus the timeline rows in order."""
    counters: Dict[str, float] = {}
    gauges: Dict[str, float] = {}
    hists: Dict[str, dict] = {}
    timeline: List[dict] = []
    meta: dict = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            r = json.loads(line)
            t = r.get("type")
            if t == "counter":
                counters[r["name"]] = float(r.get("value", 0))
            elif t == "gauge":
                gauges[r["name"]] = r.get("value")
            elif t == "hist":
                hists[r["name"]] = r
            elif t == "timeline":
                timeline.append(r)
            elif t == "meta":
                meta = r
    return {
        "counters": counters, "gauges": gauges, "hists": hists,
        "timeline": timeline, "meta": meta,
    }


def disruption_intervals(timeline: List[dict]) -> List[dict]:
    """Contiguous intervals where a timeline signal shows the service
    disrupted, with whether (and in how long) each one recovered.
    Signals: ``breakers_open > 0``, ``quarantined > 0``,
    ``ready == False``."""

    def signals(row: dict) -> List[str]:
        out = []
        if row.get("breakers_open"):
            out.append("breaker")
        if row.get("quarantined"):
            out.append("quarantine")
        if row.get("ready") is False:
            out.append("not_ready")
        return out

    intervals: List[dict] = []
    open_at: Dict[str, float] = {}
    for row in timeline:
        t = float(row.get("t", 0.0))
        active = set(signals(row))
        for sig in list(open_at):
            if sig not in active:
                t0 = open_at.pop(sig)
                intervals.append({
                    "signal": sig, "t_start": t0, "t_end": t,
                    "recovered": True, "duration_s": round(t - t0, 3),
                })
        for sig in active:
            open_at.setdefault(sig, t)
    t_last = float(timeline[-1].get("t", 0.0)) if timeline else 0.0
    for sig, t0 in sorted(open_at.items()):
        intervals.append({
            "signal": sig, "t_start": t0, "t_end": t_last,
            "recovered": False,
            "duration_s": round(t_last - t0, 3),
        })
    intervals.sort(key=lambda iv: iv["t_start"])
    return intervals


def bucket_p99s(hists: Dict[str, dict]) -> Dict[str, Tuple[float, float]]:
    """scope -> (p95, p99) of ``serve.latency.<scope>.total`` for
    per-bucket scopes (tenant./replica. aggregates are judged under
    their own flags)."""
    out: Dict[str, Tuple[float, float]] = {}
    for name, h in hists.items():
        m = _LAT_RE.match(name)
        if not m or m.group("split") != "total":
            continue
        scope = m.group("scope")
        if scope.startswith(("replica.", "tenant.")):
            continue
        out[scope] = (float(h.get("p95", 0.0)), float(h.get("p99", 0.0)))
    return out


def tenant_p99s(hists: Dict[str, dict]) -> Dict[str, float]:
    out: Dict[str, float] = {}
    for name, h in hists.items():
        m = _LAT_RE.match(name)
        if not m or m.group("split") != "total":
            continue
        scope = m.group("scope")
        if scope.startswith("tenant."):
            out[scope[len("tenant."):]] = float(h.get("p99", 0.0))
    return out


def analyze(path: str, p99_budget_ms: Optional[float] = None,
            p95_budget_ms: Optional[float] = None,
            tenant_p99_budget_ms: Optional[float] = None,
            max_compiles: int = 0, min_timeline_rows: int = 2,
            min_delivered: int = 1,
            max_recovery_s: Optional[float] = None) -> dict:
    """All verdict rows for one soak JSONL.  Each row:
    ``{check, ok, detail}``; ``usable`` False means exit 2."""
    data = load(path)
    c = data["counters"]
    g = data["gauges"]
    submitted = int(c.get("soak.submitted", 0))
    if submitted <= 0:
        return {"usable": False, "rows": [], "data": data}
    delivered = int(c.get("soak.delivered", 0))
    typed = int(c.get("soak.typed_errors", 0))
    refused = int(c.get("soak.refused", 0))
    bad = int(c.get("soak.bad_results", 0))
    rows: List[dict] = []

    acct = delivered + typed + refused
    rows.append({
        "check": "delivery completeness", "ok": acct == submitted,
        "detail": (
            f"submitted={submitted} == delivered={delivered} + "
            f"typed={typed} + refused={refused}"
            if acct == submitted else
            f"submitted={submitted} != delivered+typed+refused={acct} "
            f"({submitted - acct:+d} unaccounted)"
        ),
    })
    rows.append({
        "check": "delivered volume", "ok": delivered >= min_delivered,
        "detail": f"delivered={delivered} (floor {min_delivered})",
    })
    serve_req = c.get("serve.requests")
    admitted = submitted - refused
    if serve_req is None:
        rows.append({
            "check": "admission reconciliation", "ok": False,
            "detail": "the serve.requests counter is missing from the dump",
        })
    else:
        rows.append({
            "check": "admission reconciliation",
            "ok": int(serve_req) == admitted,
            "detail": (
                f"admitted serve.requests={int(serve_req)} == "
                f"submitted-refused={admitted}"
                if int(serve_req) == admitted else
                f"admitted serve.requests={int(serve_req)} != "
                f"submitted-refused={admitted}"
            ),
        })
    rows.append({
        "check": "integrity escapes", "ok": bad == 0,
        "detail": (
            "zero soak.bad_results (no wrong answer crossed the client "
            "boundary)" if bad == 0 else
            f"escapes soak.bad_results={bad}: finite-but-wrong X delivered"
        ),
    })
    orphans = g.get("soak.orphan_spans")
    if orphans is not None:
        rows.append({
            "check": "orphan traces", "ok": int(orphans) == 0,
            "detail": f"gauge soak.orphan_spans={int(orphans)}",
        })

    # injected <= detected: chaos_report's registry join, verbatim
    try:
        chaos_rows = _chaos().analyze(path)
    except Exception as e:  # registry unreadable: a loud verdict row
        chaos_rows = None
        rows.append({
            "check": "fault containment", "ok": False,
            "detail": f"site registry join failed: {e}",
        })
    if chaos_rows is not None:
        flagged = [r for r in chaos_rows if r["flagged"]]
        injected_total = sum(r["injected"] for r in chaos_rows)
        rows.append({
            "check": "fault containment", "ok": not flagged,
            "detail": (
                f"{len(chaos_rows)} site(s), {injected_total} injected, "
                "all joined to recovery signals" if not flagged else
                "no recovery signal from: "
                + ", ".join(
                    f"{r['site']} (injected={r['injected']})"
                    for r in flagged
                )
            ),
        })

    compiles = int(c.get("jit.compilations", 0))
    rows.append({
        "check": "steady-state compiles", "ok": compiles <= max_compiles,
        "detail": f"counted jit.compilations={compiles} (budget {max_compiles})",
    })

    scopes = bucket_p99s(data["hists"])
    if p99_budget_ms is not None:
        over = {
            s: p99 for s, (_p95, p99) in scopes.items()
            if p99 * 1e3 > p99_budget_ms
        }
        rows.append({
            "check": f"bucket p99 <= {p99_budget_ms:g}ms",
            "ok": not over,
            "detail": (
                f"{len(scopes)} bucket scope(s) inside budget"
                if not over else ", ".join(
                    f"{s}: p99={p99 * 1e3:.1f}ms"
                    for s, p99 in sorted(over.items())
                )
            ),
        })
    if p95_budget_ms is not None:
        over = {
            s: p95 for s, (p95, _p99) in scopes.items()
            if p95 * 1e3 > p95_budget_ms
        }
        rows.append({
            "check": f"bucket p95 <= {p95_budget_ms:g}ms",
            "ok": not over,
            "detail": (
                f"{len(scopes)} bucket scope(s) inside budget"
                if not over else ", ".join(
                    f"{s}: p95={p95 * 1e3:.1f}ms"
                    for s, p95 in sorted(over.items())
                )
            ),
        })
    if tenant_p99_budget_ms is not None:
        tp = tenant_p99s(data["hists"])
        over = {
            t: p99 for t, p99 in tp.items()
            if p99 * 1e3 > tenant_p99_budget_ms
        }
        rows.append({
            "check": f"tenant p99 <= {tenant_p99_budget_ms:g}ms",
            "ok": not over,
            "detail": (
                f"{len(tp)} tenant(s) inside budget" if not over
                else ", ".join(
                    f"{t}: p99={p99 * 1e3:.1f}ms"
                    for t, p99 in sorted(over.items())
                )
            ),
        })

    tl = data["timeline"]
    rows.append({
        "check": "health timeline", "ok": len(tl) >= min_timeline_rows,
        "detail": f"{len(tl)} timeline row(s) (floor {min_timeline_rows})",
    })
    intervals = disruption_intervals(tl)
    unrecovered = [iv for iv in intervals if not iv["recovered"]]
    if intervals:
        worst = max(iv["duration_s"] for iv in intervals)
        ok = not unrecovered and (
            max_recovery_s is None or worst <= max_recovery_s
        )
        rows.append({
            "check": "disruption recovery", "ok": ok,
            "detail": (
                f"{len(intervals)} disruption interval(s), all recovered, "
                f"longest {worst:.3f}s"
                + (f" (budget {max_recovery_s:g}s)"
                   if max_recovery_s is not None else "")
                if ok else
                (", ".join(
                    f"{iv['signal']} open at end "
                    f"(since t={iv['t_start']:.2f}s)"
                    for iv in unrecovered
                ) if unrecovered else
                 f"longest recovery {worst:.3f}s > "
                 f"budget {max_recovery_s:g}s")
            ),
        })

    return {
        "usable": True, "rows": rows, "data": data,
        "intervals": intervals, "scopes": scopes,
        "tenants": tenant_p99s(data["hists"]),
        "tally": {
            "submitted": submitted, "delivered": delivered,
            "typed_errors": typed, "refused": refused,
            "bad_results": bad,
        },
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__.splitlines()[0],
    )
    ap.add_argument("jsonl", help="metrics JSONL from a soak replay")
    ap.add_argument("--p99-budget-ms", type=float, default=None,
                    help="per-bucket p99 latency budget (total split)")
    ap.add_argument("--p95-budget-ms", type=float, default=None,
                    help="per-bucket p95 latency budget")
    ap.add_argument("--tenant-p99-budget-ms", type=float, default=None,
                    help="per-tenant p99 latency budget")
    ap.add_argument("--max-compiles", type=int, default=0,
                    help="allowed jit.compilations mid-soak (default 0)")
    ap.add_argument("--min-timeline-rows", type=int, default=2,
                    help="minimum {'type':'timeline'} rows (default 2)")
    ap.add_argument("--min-delivered", type=int, default=1,
                    help="minimum delivered results (default 1)")
    ap.add_argument("--max-recovery-s", type=float, default=None,
                    help="budget for the longest disruption interval")
    args = ap.parse_args(argv)

    res = analyze(
        args.jsonl, p99_budget_ms=args.p99_budget_ms,
        p95_budget_ms=args.p95_budget_ms,
        tenant_p99_budget_ms=args.tenant_p99_budget_ms,
        max_compiles=args.max_compiles,
        min_timeline_rows=args.min_timeline_rows,
        min_delivered=args.min_delivered,
        max_recovery_s=args.max_recovery_s,
    )
    if not res["usable"]:
        print(f"{args.jsonl}: no soak.submitted counter — not a soak "
              "run's JSONL (replay not driven, or metrics off)",
              file=sys.stderr)
        return 2

    t = res["tally"]
    print(f"soak verdict: {args.jsonl}")
    print(f"  submitted={t['submitted']} delivered={t['delivered']} "
          f"typed={t['typed_errors']} refused={t['refused']} "
          f"bad={t['bad_results']}")
    if res["scopes"]:
        print("  bucket tails (total split):")
        for s, (p95, p99) in sorted(res["scopes"].items()):
            print(f"    {s:40} p95={p95 * 1e3:8.1f}ms p99={p99 * 1e3:8.1f}ms")
    if res["tenants"]:
        print("  tenant tails: " + "  ".join(
            f"{k}={v * 1e3:.1f}ms" for k, v in sorted(res["tenants"].items())
        ))
    print()
    failed = 0
    for row in res["rows"]:
        mark = "ok  " if row["ok"] else "FAIL"
        if not row["ok"]:
            failed += 1
        print(f"  [{mark}] {row['check']}: {row['detail']}")
    print()
    if failed:
        print(f"{failed} check(s) failed")
        return 1
    print("all checks passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
