#!/usr/bin/env python
"""Merge several metrics JSONL dumps into one (multi-process soaks,
sharded gates, replica-per-process runs).

Every ``SLATE_TPU_METRICS`` dump is one process's registry; a soak
that spans processes (sharded serve, subprocess drivers) leaves N
dumps that no report can read together.  This tool folds them into a
single dump with the SAME schema, so every ``tools/*_report.py``
judge runs unchanged on the merged view:

* **counters** sum — they are monotonic totals per process.
* **gauges** last-wins in argument order — point-in-time snapshots,
  same rule the loaders apply to re-dumped lines within one file.
* **timers** merge exactly: count/total sum, min/max envelope.
* **histograms** merge bucket-wise: every dump's ``[le, count]`` rows
  sit on the one shared ``HIST_EDGES`` lattice (1e-6s..1000s, 10
  buckets/decade — aux/metrics.py), so merging is per-edge count
  addition, then p50/p95/p99 re-rank from the merged counts with the
  library's own geometric in-bucket interpolation, replicated here.
  An edge not on the lattice is a schema violation and fails loudly.
* **timeline** rows pass through (tagged ``"src"`` with the dump's
  basename, or its ``--tag`` when given) and re-sort by ``t`` — N
  health timelines interleave into one.
* **event** rows are dropped: per-process debug traces do not
  interleave meaningfully across unsynchronized clocks.
* **cost** rows last-wins per executable name (cumulative snapshots).

``--tag`` (one per input, in order — e.g. ``--tag host0 --tag host1``
for a fleet's per-host dumps) extends the timeline's src-tagging to
counters, gauges, timers and histograms: each input's OWN rows are
also emitted, carrying ``"src": <tag>``, BEFORE the untagged global
rows — so a per-host judge can attribute a counter to the host that
emitted it, while every existing report (last-wins loaders included)
still lands on the preserved global sums.

Usage:
    python tools/metrics_merge.py a.jsonl b.jsonl > merged.jsonl
    python tools/metrics_merge.py shard*.jsonl -o merged.jsonl
    python tools/metrics_merge.py --tag router --tag host0 \\
        router.jsonl host0.metrics.jsonl -o merged.jsonl
    python tools/soak_report.py merged.jsonl
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
import time
from typing import Dict, List, Optional

# the shared histogram lattice, replicated from slate_tpu/aux/metrics.py
# (this tool is stdlib-only by contract — reports must work when the
# library itself is broken)
HIST_PER_DECADE = 10
HIST_LO_S = 1e-6
HIST_EDGES = tuple(
    HIST_LO_S * 10.0 ** (i / HIST_PER_DECADE)
    for i in range(9 * HIST_PER_DECADE + 1)
)
#: wire-format edge labels, exactly as Histogram.bucket_rows writes them
_EDGE_INDEX = {
    float(f"{e:.9g}"): i for i, e in enumerate(HIST_EDGES)
}
_OVERFLOW = len(HIST_EDGES)


def percentile_from(counts: List[int], p: float,
                    lo: Optional[float] = None,
                    hi: Optional[float] = None) -> Optional[float]:
    """aux/metrics.Histogram.percentile_from, replicated: rank into
    the lattice, geometric interpolation inside the landing bucket,
    clamped to the observed [min, max] envelope."""
    total = sum(counts)
    if total <= 0:
        return None
    rank = max(1, math.ceil(p / 100.0 * total))
    cum = 0
    for i, k in enumerate(counts):
        cum += k
        if cum >= rank:
            if i == 0:
                est = lo if lo is not None else HIST_LO_S
            elif i >= len(HIST_EDGES):
                est = hi if hi is not None else HIST_EDGES[-1]
            else:
                b_lo, b_hi = HIST_EDGES[i - 1], HIST_EDGES[i]
                frac = (rank - (cum - k)) / max(k, 1)
                est = b_lo * (b_hi / b_lo) ** frac
            if lo is not None:
                est = max(est, lo)
            if hi is not None:
                est = min(est, hi)
            return est
    return None


class _MergedHist:
    def __init__(self) -> None:
        self.counts = [0] * (_OVERFLOW + 1)
        self.count = 0
        self.total = 0.0
        self.min = math.inf
        self.max = 0.0

    def fold(self, row: dict, path: str) -> None:
        for le, k in row.get("buckets", ()):
            if le == "inf":
                i = _OVERFLOW
            else:
                i = _EDGE_INDEX.get(float(le))
                if i is None:
                    raise SystemExit(
                        f"metrics_merge: {path}: hist {row['name']!r} "
                        f"bucket edge {le!r} is not on the shared "
                        "HIST_EDGES lattice — refusing to merge "
                        "mismatched schemas"
                    )
            self.counts[i] += int(k)
        self.count += int(row.get("count", 0))
        self.total += float(row.get("total_s", 0.0))
        mn = row.get("min_s")
        if mn is not None and int(row.get("count", 0)) > 0:
            self.min = min(self.min, float(mn))
        self.max = max(self.max, float(row.get("max_s", 0.0)))

    def row(self, name: str) -> dict:
        lo = self.min if self.count else None
        hi = self.max if self.count else None
        return {
            "type": "hist", "name": name,
            "count": self.count,
            "total_s": round(self.total, 6),
            "min_s": round(self.min, 6) if self.count else 0.0,
            "max_s": round(self.max, 6),
            "p50": round(percentile_from(self.counts, 50, lo, hi) or 0.0, 6),
            "p95": round(percentile_from(self.counts, 95, lo, hi) or 0.0, 6),
            "p99": round(percentile_from(self.counts, 99, lo, hi) or 0.0, 6),
            "buckets": [
                [
                    "inf" if i >= _OVERFLOW
                    else float(f"{HIST_EDGES[i]:.9g}"),
                    k,
                ]
                for i, k in enumerate(self.counts) if k
            ],
        }


def merge(paths: List[str],
          tags: Optional[List[str]] = None) -> List[dict]:
    """All merged rows in dump order: meta, timeline, [src-tagged
    per-input rows when ``tags`` is given], counter, gauge, timer,
    hist, cost.  ``tags`` pairs with ``paths`` positionally."""
    if tags and len(tags) != len(paths):
        raise SystemExit(
            f"metrics_merge: {len(tags)} --tag values for "
            f"{len(paths)} inputs — they pair positionally"
        )
    counters: Dict[str, float] = {}
    gauges: Dict[str, object] = {}
    timers: Dict[str, list] = {}
    hists: Dict[str, _MergedHist] = {}
    costs: Dict[str, dict] = {}
    timeline: List[dict] = []
    tagged: List[dict] = []
    schema = None
    for pi, path in enumerate(paths):
        src = tags[pi] if tags else os.path.basename(path)
        mine: Dict[str, dict] = {}  # this input's own rows, by (type, name)
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                r = json.loads(line)
                t = r.get("type")
                if t == "counter":
                    counters[r["name"]] = (
                        counters.get(r["name"], 0.0) + float(r["value"])
                    )
                elif t == "gauge":
                    gauges[r["name"]] = r["value"]
                elif t == "timer":
                    cur = timers.get(r["name"])
                    if cur is None:
                        timers[r["name"]] = [
                            int(r["count"]), float(r["total_s"]),
                            float(r["min_s"]), float(r["max_s"]),
                        ]
                    else:
                        cur[0] += int(r["count"])
                        cur[1] += float(r["total_s"])
                        cur[2] = min(cur[2], float(r["min_s"]))
                        cur[3] = max(cur[3], float(r["max_s"]))
                elif t == "hist":
                    hists.setdefault(r["name"], _MergedHist()).fold(r, path)
                elif t == "timeline":
                    row = dict(r)
                    row["src"] = src
                    timeline.append(row)
                elif t == "cost":
                    costs[r["name"]] = r
                elif t == "meta":
                    if schema is None:
                        schema = r.get("schema")
                # event rows: dropped (module docstring)
                if tags and t in ("counter", "gauge", "timer", "hist"):
                    # per-input view: the same fold rules applied to
                    # this input alone (re-dumped files repeat names)
                    key = (t, r["name"])
                    cur = mine.get(key)
                    if t == "counter":
                        if cur is None:
                            mine[key] = dict(r)
                        else:
                            cur["value"] = (
                                float(cur["value"]) + float(r["value"])
                            )
                    elif t == "gauge":
                        mine[key] = dict(r)
                    elif t == "timer":
                        if cur is None:
                            mine[key] = dict(r)
                        else:
                            cur["count"] = int(cur["count"]) + int(r["count"])
                            cur["total_s"] = (
                                float(cur["total_s"]) + float(r["total_s"])
                            )
                            cur["min_s"] = min(
                                float(cur["min_s"]), float(r["min_s"])
                            )
                            cur["max_s"] = max(
                                float(cur["max_s"]), float(r["max_s"])
                            )
                    else:  # hist
                        if cur is None:
                            mine[key] = _MergedHist()
                        mine[key].fold(r, path)
        for (t, name) in sorted(mine):
            row = mine[(t, name)]
            if isinstance(row, _MergedHist):
                row = row.row(name)
            row["src"] = src
            tagged.append(row)
    timeline.sort(key=lambda r: float(r.get("t", 0.0)))
    out: List[dict] = [{
        "type": "meta", "schema": schema if schema is not None else 1,
        "unix_time": time.time(),
        "merged_from": [os.path.basename(p) for p in paths],
    }]
    out.extend(timeline)
    # src-tagged per-input rows FIRST: a last-wins loader that ignores
    # "src" then still finishes on the untagged global merge below
    out.extend(tagged)
    out.extend(
        {"type": "counter", "name": n, "value": counters[n]}
        for n in sorted(counters)
    )
    out.extend(
        {"type": "gauge", "name": n, "value": gauges[n]}
        for n in sorted(gauges)
    )
    for n in sorted(timers):
        cnt, total, mn, mx = timers[n]
        out.append({
            "type": "timer", "name": n, "count": cnt,
            "total_s": round(total, 6), "min_s": round(mn, 6),
            "max_s": round(mx, 6),
        })
    out.extend(hists[n].row(n) for n in sorted(hists))
    out.extend(costs[n] for n in sorted(costs))
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("jsonl", nargs="+", help="metrics dumps to merge")
    ap.add_argument("-o", "--output", default=None,
                    help="write here instead of stdout")
    ap.add_argument("--tag", action="append", default=None,
                    help="src tag for the Nth input (repeat per input; "
                         "emits per-input counter/gauge/timer/hist rows "
                         "tagged 'src' alongside the global merge)")
    args = ap.parse_args(argv)
    rows = merge(args.jsonl, tags=args.tag)
    out = (
        open(args.output, "w") if args.output else sys.stdout
    )
    try:
        for r in rows:
            out.write(json.dumps(r) + "\n")
    finally:
        if args.output:
            out.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
