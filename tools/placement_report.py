#!/usr/bin/env python
"""Per-replica placement table from a metrics JSONL.

    python tools/placement_report.py out.jsonl [--min-requests 8]

Rows come from the placement-tier metrics the SolverService emits
(slate_tpu/serve/service.py): ``serve.replica.<name>.dispatched``
counters (requests each replica lane executed — the sharded lane is
``serve.replica.sharded.*``), ``serve.replica.<name>.queue_depth``
gauges (last snapshot), and the per-replica breaker transition
counters ``serve.replica.<name>.breaker_open`` / ``breaker_closed``.
The routing split (``serve.replicated_dispatch`` vs
``serve.routed_sharded``) prints underneath.

Exit status is the **scale-out verdict**: once the replicated tier has
seen at least ``--min-requests`` dispatches, a *starved* replica — one
that dispatched nothing while its peers worked — exits nonzero.  A
starved replica means the placement policy is not spreading load
(mis-selected strategy, a wedged worker, or a breaker stuck open), so
the ``run_tests.py --sharded`` gate fails on it.

Produce the JSONL with ``SLATE_TPU_METRICS=out.jsonl`` around any
serving workload (examples/ex20_sharded_serving.py shows the loop).
"""

import argparse
import json
import re
import sys

_REPLICA_RE = re.compile(
    r"^serve\.replica\.(?P<name>[^.]+)\.(?P<field>dispatched|queue_depth"
    r"|breaker_open|breaker_closed|removed)$"
)


def load_records(path):
    counters, gauges = {}, {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            r = json.loads(line)
            # cumulative snapshots: last value wins (same semantics as
            # chaos_report/artifact_report — summing re-dumped JSONLs
            # would inflate)
            if r.get("type") == "counter":
                counters[r["name"]] = r.get("value", 0)
            elif r.get("type") == "gauge":
                gauges[r["name"]] = r.get("value", 0)
    return counters, gauges


def replica_rows(counters, gauges):
    rows = {}
    for src in (counters, gauges):
        for name, value in src.items():
            m = _REPLICA_RE.match(name)
            if not m:
                continue
            row = rows.setdefault(m.group("name"), {
                "dispatched": 0, "queue_depth": 0,
                "breaker_open": 0, "breaker_closed": 0, "removed": 0,
            })
            row[m.group("field")] = int(value)
    return rows


def _order(name):
    # numeric replicas first (in order), the sharded lane last
    return (0, int(name)) if name.isdigit() else (1, 0)


def main(argv=None):
    ap = argparse.ArgumentParser(prog="placement_report")
    ap.add_argument("jsonl", help="metrics JSONL (SLATE_TPU_METRICS output)")
    ap.add_argument("--min-requests", type=int, default=8,
                    help="replicated dispatches before the starvation "
                         "verdict applies (default 8)")
    args = ap.parse_args(argv)

    counters, gauges = load_records(args.jsonl)
    rows = replica_rows(counters, gauges)
    if not rows:
        print("(no serve.replica.* metrics in this JSONL — did the "
              "stream go through a SolverService?)")
        return 0

    hdr = (f"{'replica':>8} {'state':>8} {'dispatched':>11} "
           f"{'queue_depth':>12} {'breaker_open':>13} "
           f"{'breaker_closed':>15}")
    print(hdr)
    print("-" * len(hdr))
    for name in sorted(rows, key=_order):
        r = rows[name]
        # an elastically removed lane stays a (terminal) row: its
        # dispatch history is part of the run's story, it just stops
        # counting toward live-fleet verdicts
        state = "removed" if r["removed"] else "live"
        print(f"{name:>8} {state:>8} {r['dispatched']:11d} "
              f"{r['queue_depth']:12d} {r['breaker_open']:13d} "
              f"{r['breaker_closed']:15d}")

    replicated = int(counters.get("serve.replicated_dispatch", 0))
    sharded = int(counters.get("serve.routed_sharded", 0))
    print(f"\nrouting: {replicated} replicated, {sharded} sharded "
          f"(serve.replicated_dispatch / serve.routed_sharded)")

    # the scale-out verdict: a LIVE replica lane that dispatched
    # nothing while the tier worked is starved (a removed lane is a
    # terminal state, not a starving one — a short-lived burst lane
    # legitimately ends with few or zero dispatches)
    lanes = {
        n: r for n, r in rows.items()
        if n.isdigit() and not r["removed"]
    }
    total = sum(r["dispatched"] for r in lanes.values())
    rc = 0
    if len(lanes) > 1 and total >= args.min_requests:
        starved = sorted(
            (n for n, r in lanes.items() if r["dispatched"] == 0),
            key=_order,
        )
        if starved:
            print(f"FAIL: replica(s) {', '.join(starved)} starved — "
                  f"{total} dispatches never reached them (placement "
                  "not spreading load)")
            rc = 1
        else:
            print(f"scale-out ok: all {len(lanes)} replicas dispatched")
    return rc


if __name__ == "__main__":
    sys.exit(main())
