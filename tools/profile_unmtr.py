"""Stage-3 back-transform experiments at n=4096 (unmtr_hb2st is the
post-stedc wall-clock ceiling: ~50 s of stage 3's 50.2 s).

Variant A: current (per-sweep contiguous slice over all of Z).
Variant B: column panels — outer python loop over Z column blocks,
inner fori over sweeps; if XLA keeps the panel carry VMEM-resident the
HBM traffic drops ~100x, else it matches A.
"""
import os, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.expanduser("~/.cache/jax_comp"))
import numpy as np

def main():
    import jax
    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp
    from jax import lax
    from slate_tpu.ops.bulge import unmtr_hb2st

    print(f"device: {jax.devices()[0]}", flush=True)
    rng = np.random.default_rng(0)
    n, b = 4096, 128
    n_sweeps = n - 2
    J1 = (n - 3) // b + 2
    VS = jnp.asarray(rng.standard_normal((n_sweeps, J1, b)) * 0.1)
    VS = VS.at[:, :, 0].set(1.0)
    TAUS = jnp.asarray(rng.standard_normal((n_sweeps, J1)) * 0.5)
    Z = jnp.asarray(rng.standard_normal((n, n)))

    def timed(fn, *a):
        def run(args):
            out = fn(*args)
            return float(np.asarray(out.ravel()[-1]))
        for attempt in range(4):
            try:
                run(a); break
            except Exception as e:
                print(f" [retry {type(e).__name__}]", flush=True)
                time.sleep(15)
        t0 = time.time()
        run((a[0], a[1], a[2] + 1e-13) if len(a) == 3 else a)
        return time.time() - t0

    fA = jax.jit(lambda VS, TAUS, Z: unmtr_hb2st(VS, TAUS, Z, n, b))
    tA = timed(fA, VS, TAUS, Z)
    print(f"variant A (full-width slices): {tA:.2f}s", flush=True)

    w = 512

    def panel_apply(VS, TAUS, Zp):
        # Zp: (n + pad, w) one column panel
        def sweep(k, Zp):
            s = n_sweeps - 1 - k
            v = VS[s]
            tau = TAUS[s]
            Zr = lax.dynamic_slice(Zp, (s + 1, 0), (J1 * b, w)).reshape(
                J1, b, w)
            wrow = jnp.einsum("jb,jbm->jm", v, Zr)
            Zr = Zr - tau[:, None, None] * v[:, :, None] * wrow[:, None, :]
            return lax.dynamic_update_slice(
                Zp, Zr.reshape(-1, w), (s + 1, 0))
        return lax.fori_loop(0, n_sweeps, sweep, Zp)

    fB = jax.jit(panel_apply)
    pad = b + J1 * b + 8
    Zp0 = jnp.pad(Z[:, :w], ((0, pad), (0, 0)))
    tB = timed(fB, VS, TAUS, Zp0)
    print(f"variant B ({w}-col panel, ONE panel): {tB:.2f}s "
          f"-> est. full: {tB * (n // w):.1f}s", flush=True)

if __name__ == "__main__":
    main()
