#!/usr/bin/env python
"""Silent-data-corruption verdict over a metrics JSONL.

Reads a ``SLATE_TPU_METRICS`` dump from a run with the ``sdc_factor``
/ ``sdc_solve`` chaos sites armed and judges the integrity plane
(``slate_tpu/integrity``, ``Option.ServeIntegrity``):

* **escape check** — every injected SDC must land on a detection
  counter: ``serve.integrity.fail`` (a delivery certificate caught the
  wrong X) or ``serve.factor_cache.stale`` (the factor-cache residual
  fence caught a poisoned cached factor).  Injections exceeding the
  summed detections mean finite-but-wrong answers reached clients
  unflagged — the exact failure mode the plane exists for — and the
  tool exits nonzero.
* **containment check** — certificate failures must resolve: each
  failed request either recovered (a re-execution delivered a PASSING
  result, ``serve.integrity.recovered``) or was refused typed
  (``serve.integrity.abandoned``).  Failures with neither signal mean
  requests vanished.

Also renders the hedging triple (``serve.hedge.{sent,won,wasted}``)
and the quarantine transitions (``serve.integrity.quarantined`` /
``.unquarantined`` + the per-replica ``serve.replica.<i>.quarantined``
family) so one report answers: was corruption detected, was it
contained, did the hedges win, did the sick lane quarantine and heal.

Usage:
    SLATE_TPU_METRICS=/tmp/sdc.jsonl python my_serving_app.py
    python tools/integrity_report.py /tmp/sdc.jsonl
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict

SDC_SITES = ("sdc_factor", "sdc_solve")
INJECT_PREFIX = "faults.injected."


def _counters(path: str) -> Dict[str, float]:
    out: Dict[str, float] = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            row = json.loads(line)
            if row.get("type") == "counter":
                out[row["name"]] = float(row.get("value", 0))
    return out


def analyze(path: str) -> dict:
    c = _counters(path)
    injected = {
        site: int(c.get(INJECT_PREFIX + site, 0)) for site in SDC_SITES
    }
    detected_fail = int(c.get("serve.integrity.fail", 0))
    detected_stale = int(c.get("serve.factor_cache.stale", 0))
    total_injected = sum(injected.values())
    recovered = int(c.get("serve.integrity.recovered", 0))
    abandoned = int(c.get("serve.integrity.abandoned", 0))
    # pooled escape math, faithful to SITE_SPECS: BOTH sites list the
    # certificate counter AND the factor-cache stale fence as recovery
    # families (an sdc_solve firing on a solve-phase HIT dispatch is
    # caught by the residual fence and counted stale, not fail — a
    # per-site split would flag that correctly-contained run as an
    # escape).  The counters are process-global, so one site's
    # detections can mask the other's escapes — the chaos_report
    # shared-attribution caveat; for airtight per-site attribution,
    # run one site per pass (the per-site injected counts printed
    # below are the operator's cue).
    detected = detected_fail + detected_stale
    escaped = max(total_injected - detected, 0)
    # containment: every certificate failure eventually recovered or
    # was refused typed.  A single request can fail several
    # certificates before recovering, so fails >= recovered+abandoned
    # is normal — zero resolution signal against nonzero fails is not.
    unresolved = detected_fail > 0 and recovered + abandoned == 0
    return {
        "injected": injected,
        "total_injected": total_injected,
        "detected_fail": detected_fail,
        "detected_stale": detected_stale,
        "checked": int(c.get("serve.integrity.checked", 0)),
        "recovered": recovered,
        "abandoned": abandoned,
        "escaped": escaped,
        "unresolved": unresolved,
        "hedge": {
            "sent": int(c.get("serve.hedge.sent", 0)),
            "won": int(c.get("serve.hedge.won", 0)),
            "wasted": int(c.get("serve.hedge.wasted", 0)),
        },
        "quarantined": int(c.get("serve.integrity.quarantined", 0)),
        "unquarantined": int(c.get("serve.integrity.unquarantined", 0)),
        "replicas": {
            name[len("serve.replica."):-len(".quarantined")]: int(v)
            for name, v in c.items()
            if name.startswith("serve.replica.")
            and name.endswith(".quarantined")
        },
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("jsonl", help="metrics JSONL from an SDC chaos run")
    args = ap.parse_args(argv)

    r = analyze(args.jsonl)
    print(f"{'injected':>22}: " + "  ".join(
        f"{s}={n}" for s, n in r["injected"].items()
    ))
    print(f"{'certificates checked':>22}: {r['checked']}")
    print(f"{'detected':>22}: certificate_fail={r['detected_fail']}  "
          f"factor_stale={r['detected_stale']}")
    print(f"{'contained':>22}: recovered={r['recovered']}  "
          f"abandoned_typed={r['abandoned']}")
    h = r["hedge"]
    print(f"{'hedges':>22}: sent={h['sent']}  won={h['won']}  "
          f"wasted={h['wasted']}")
    print(f"{'quarantine':>22}: entered={r['quarantined']}  "
          f"recovered={r['unquarantined']}"
          + ("  per-replica " + ", ".join(
              f"{k}={v}" for k, v in sorted(r["replicas"].items())
          ) if r["replicas"] else ""))

    if r["total_injected"] == 0:
        print("\nno sdc_factor/sdc_solve injections in this JSONL "
              "(faults off?)")
        return 0
    bad = 0
    if r["escaped"] > 0:
        print(f"\nFAIL: {r['escaped']} injected SDC event(s) escaped "
              "certification — finite wrong answers were delivered "
              "unflagged")
        bad = 1
    if r["unresolved"]:
        print("\nFAIL: certificate failures with zero recovery/abandon "
              "signal — failed requests vanished")
        bad = 1
    if not bad:
        print("\nevery injected SDC was detected and contained")
    return bad


if __name__ == "__main__":
    sys.exit(main())
